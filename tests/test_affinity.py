"""Affinity tier (BASELINE config 4): inter-pod affinity/anti-affinity —
the quadratic pod x pod term — oracle semantics and oracle<->device parity.

The oracle driver mirrors test_device_parity.oracle_schedule but adds the
InterPodAffinity predicate wired with the live pod lister (so in-batch
assumed pods participate in the quadratic term, as the device carry does).
"""

import copy
import random

import pytest

from kubernetes_tpu.core import types as api
from kubernetes_tpu.sched import predicates as preds
from kubernetes_tpu.sched import priorities as prios
from kubernetes_tpu.sched.device import ClusterSnapshot, schedule_batch
from kubernetes_tpu.sched.generic import (FitError, GenericScheduler,
                                          NoNodesAvailable)
from kubernetes_tpu.sched.listers import (FakeControllerLister,
                                          FakeNodeLister, FakePodLister,
                                          FakeServiceLister)
from kubernetes_tpu.sched.priorities import SelectorSpread

from test_device_parity import MI, make_node, rand_cluster


def aff(selector, topo="zone", anti=False, namespaces=()):
    term = api.PodAffinityTerm(label_selector=dict(selector),
                               namespaces=list(namespaces),
                               topology_key=topo)
    if anti:
        return api.Affinity(
            pod_anti_affinity=api.PodAntiAffinity(
                required_during_scheduling=[term]))
    return api.Affinity(
        pod_affinity=api.PodAffinity(required_during_scheduling=[term]))


def pod(name, labels=None, affinity=None, ns="default", node=None,
        phase="Pending"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace=ns,
                                labels=labels or {}),
        spec=api.PodSpec(
            containers=[api.Container(name="c", image="img")],
            node_name=node or "", affinity=affinity),
        status=api.PodStatus(phase=phase))


def nodes_ab():
    return [make_node("node-a1", 4000, 2048 * MI, 110, {"zone": "a"}),
            make_node("node-a2", 4000, 2048 * MI, 110, {"zone": "a"}),
            make_node("node-b1", 4000, 2048 * MI, 110, {"zone": "b"}),
            make_node("node-nolabel", 4000, 2048 * MI, 110, {})]


def run_predicate(p, existing, nodes, node):
    lister = FakePodLister(existing)
    by_name = {n.metadata.name: n for n in nodes}
    pred = preds.new_inter_pod_affinity_predicate(lister, by_name.get)
    return pred(p, existing, node)[0]


class TestOracle:
    def test_affinity_requires_colocated_peer(self):
        ns = nodes_ab()
        existing = [pod("peer", {"app": "db"}, node="node-a1",
                        phase="Running")]
        p = pod("new", {"app": "web"}, aff({"app": "db"}))
        assert run_predicate(p, existing, ns, ns[0])      # zone a
        assert run_predicate(p, existing, ns, ns[1])      # zone a, other node
        assert not run_predicate(p, existing, ns, ns[2])  # zone b
        assert not run_predicate(p, existing, ns, ns[3])  # keyless node

    def test_anti_affinity_excludes_domain(self):
        ns = nodes_ab()
        existing = [pod("peer", {"app": "web"}, node="node-a1",
                        phase="Running")]
        p = pod("new", {"app": "web"}, aff({"app": "web"}, anti=True))
        assert not run_predicate(p, existing, ns, ns[0])
        assert not run_predicate(p, existing, ns, ns[1])  # same domain
        assert run_predicate(p, existing, ns, ns[2])
        assert run_predicate(p, existing, ns, ns[3])      # keyless passes

    def test_bootstrap_first_self_affine_pod(self):
        ns = nodes_ab()
        p = pod("first", {"app": "web"}, aff({"app": "web"}))
        # no pod matches anywhere; the pod matches its own term -> allowed
        assert run_predicate(p, [], ns, ns[0])
        # a matching unassigned pod kills the bootstrap but satisfies
        # no domain -> all nodes fail
        floating = pod("float", {"app": "web"})
        assert not run_predicate(p, [floating], ns, ns[0])

    def test_no_bootstrap_without_self_match(self):
        ns = nodes_ab()
        p = pod("new", {"app": "web"}, aff({"app": "db"}))
        assert not run_predicate(p, [], ns, ns[0])

    def test_namespace_scoping(self):
        ns = nodes_ab()
        existing = [pod("peer", {"app": "db"}, ns="other", node="node-a1",
                        phase="Running")]
        same_ns = pod("new", {"app": "web"}, aff({"app": "db"}))
        assert not run_predicate(same_ns, existing, ns, ns[0])
        cross = pod("new2", {"app": "web"},
                    aff({"app": "db"}, namespaces=["other"]))
        assert run_predicate(cross, existing, ns, ns[0])

    def test_succeeded_pods_ignored(self):
        ns = nodes_ab()
        existing = [pod("done", {"app": "db"}, node="node-a1",
                        phase="Succeeded")]
        p = pod("new", {"app": "web"}, aff({"app": "db"}))
        assert not run_predicate(p, existing, ns, ns[0])


# --------------------------------------------------- oracle <-> device


def oracle_schedule_affinity(snap: ClusterSnapshot):
    existing = list(snap.existing_pods)
    svc_lister = FakeServiceLister(snap.services)
    rc_lister = FakeControllerLister(snap.controllers)
    node_lister = FakeNodeLister(snap.nodes)
    by_name = {n.metadata.name: n for n in snap.nodes}
    out = []
    for p in snap.pending_pods:
        pod_lister = FakePodLister(existing)
        spread = SelectorSpread(svc_lister, rc_lister)
        gs = GenericScheduler(
            {"PodFitsHostPorts": preds.pod_fits_host_ports,
             "PodFitsResources": preds.pod_fits_resources,
             "NoDiskConflict": preds.no_disk_conflict,
             "MatchNodeSelector": preds.pod_selector_matches,
             "HostName": preds.pod_fits_host,
             "InterPodAffinity": preds.new_inter_pod_affinity_predicate(
                 pod_lister, by_name.get)},
            [(prios.least_requested_priority, 1),
             (prios.balanced_resource_allocation, 1),
             (spread.calculate_spread_priority, 1)],
            pod_lister)
        try:
            host = gs.schedule(p, node_lister)
        except (FitError, NoNodesAvailable):
            out.append(None)
            continue
        out.append(host)
        bound = copy.deepcopy(p)
        bound.spec.node_name = host
        existing.append(bound)
    return out


def with_random_affinity(snap: ClusterSnapshot, seed) -> ClusterSnapshot:
    rng = random.Random(seed)
    for p in snap.pending_pods:
        r = rng.random()
        if r < 0.55:
            continue
        app = rng.choice(["web", "db", "cache"])
        topo = rng.choice(["zone", "zone", "disk"])
        anti = r > 0.8
        namespaces = []
        if rng.random() < 0.15:
            namespaces = [rng.choice(["default", "kube-system"])]
        p.spec.affinity = aff({"app": app}, topo=topo, anti=anti,
                              namespaces=namespaces)
        if rng.random() < 0.2:  # both kinds on one pod
            other = rng.choice(["web", "db"])
            extra = api.PodAffinityTerm(label_selector={"app": other},
                                        topology_key="zone")
            if anti:
                p.spec.affinity.pod_affinity = api.PodAffinity(
                    required_during_scheduling=[extra])
            else:
                p.spec.affinity.pod_anti_affinity = api.PodAntiAffinity(
                    required_during_scheduling=[extra])
    return snap


@pytest.mark.parametrize("seed", range(6))
def test_engine_matches_oracle_with_affinity(seed):
    snap = with_random_affinity(rand_cluster(seed + 100), seed)
    assert schedule_batch(snap) == oracle_schedule_affinity(snap)


def test_offtable_node_peers_occupy_their_domain():
    # A peer on a cached-but-unschedulable node still occupies its zone:
    # anti-affinity must exclude that zone, affinity must accept it
    # (parity with the serial predicate resolving via the full node cache).
    candidates = nodes_ab()[:3]           # a1, a2 (zone a), b1 (zone b)
    notready = make_node("node-x", 4000, 2048 * MI, 110, {"zone": "a"})
    peer = pod("peer", {"app": "db"}, node="node-x", phase="Running")

    anti_pod = pod("anti", {"app": "web"}, aff({"app": "db"}, anti=True))
    snap = ClusterSnapshot(nodes=candidates, existing_pods=[peer],
                           pending_pods=[anti_pod],
                           all_nodes=candidates + [notready])
    assert schedule_batch(snap) == ["node-b1"]

    aff_pod = pod("aff", {"app": "web"}, aff({"app": "db"}))
    snap = ClusterSnapshot(nodes=candidates, existing_pods=[peer],
                           pending_pods=[aff_pod],
                           all_nodes=candidates + [notready])
    got = schedule_batch(snap)
    assert got[0] in ("node-a1", "node-a2")
    # and the serial oracle agrees when its node_by_name spans the cache
    lister = FakePodLister([peer])
    by_name = {n.metadata.name: n
               for n in candidates + [notready]}
    pred = preds.new_inter_pod_affinity_predicate(lister, by_name.get)
    assert not pred(anti_pod, [peer], candidates[0])[0]
    assert pred(anti_pod, [peer], candidates[2])[0]
    assert pred(aff_pod, [peer], candidates[0])[0]


def test_engine_anti_affinity_spreads_batch():
    # 3 self-anti-affine pods over 2 zones: third pod must fail
    nodes = nodes_ab()[:3]  # a1, a2, b1 -> zones {a, b}
    pods = [pod(f"p{i}", {"app": "web"}, aff({"app": "web"}, anti=True))
            for i in range(3)]
    snap = ClusterSnapshot(nodes=nodes, pending_pods=pods)
    got = schedule_batch(snap)
    assert got == oracle_schedule_affinity(snap)
    assert got[2] is None
    assert {g.split("-")[1][0] for g in got[:2]} == {"a", "b"}


def test_engine_affinity_colocates_batch():
    nodes = nodes_ab()
    pods = [pod(f"p{i}", {"app": "web"}, aff({"app": "web"}))
            for i in range(4)]
    snap = ClusterSnapshot(nodes=nodes, pending_pods=pods)
    got = schedule_batch(snap)
    assert got == oracle_schedule_affinity(snap)
    # first pod bootstraps; the rest must land in its zone
    zones = {"node-a1": "a", "node-a2": "a", "node-b1": "b"}
    assert None not in got
    assert len({zones[g] for g in got}) == 1


def test_cordoned_node_still_resolves_topology_domain():
    """A cordoned node (spec.unschedulable=true) leaves the CANDIDATE
    list but must keep resolving its labels for affinity domains — the
    reference pairs its filtered node watch with a NodeInfo that hits
    the live nodes API, so peer pods on cordoned nodes keep occupying
    their domains (factory.go CreateFromKeys NodeInfo)."""
    import time as _time

    from kubernetes_tpu.api.client import InProcClient
    from kubernetes_tpu.api.registry import Registry
    from kubernetes_tpu.core.quantity import parse_quantity
    from kubernetes_tpu.sched.factory import ConfigFactory

    def wait_until(cond, timeout=10.0):
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            if cond():
                return True
            _time.sleep(0.05)
        return cond()

    registry = Registry()
    client = InProcClient(registry)
    for name, zone, unsched in (("n-a1", "a", True), ("n-a2", "a", False),
                                ("n-b1", "b", False)):
        registry.create("nodes", api.Node(
            metadata=api.ObjectMeta(name=name, labels={"zone": zone}),
            spec=api.NodeSpec(unschedulable=unsched),
            status=api.NodeStatus(
                capacity={"cpu": parse_quantity("4"),
                          "memory": parse_quantity("8Gi"),
                          "pods": parse_quantity("40")},
                conditions=[api.NodeCondition(type="Ready",
                                              status="True")])))
    f = ConfigFactory(client, rate_limit=False).start()
    try:
        assert wait_until(lambda: len(f.node_informer.cache.list()) == 3)
        # candidates exclude the cordoned node; NodeInfo still sees it
        assert sorted(n.metadata.name for n in f.node_lister.list()) == \
            ["n-a2", "n-b1"]
        assert f.node_lister.get("n-a1") is not None
        assert f.node_lister.get("n-a1").metadata.labels["zone"] == "a"
    finally:
        f.stop()
