"""OIDC RS256 verification (ref: plugin/pkg/auth/authenticator/token/
oidc/oidc.go — RS256 ID tokens validated against the provider JWKS).
Covers accept, wrong-key reject, alg-confusion (RS256 key replayed as
HS256 secret), alg=none, kid routing, and raw PKCS#1 v1.5 vectors."""

import base64
import hashlib
import time

import pytest

from kubernetes_tpu.auth import rsa as rsapkg
from kubernetes_tpu.auth.authenticate import (JWTAuthenticator, make_jwt,
                                              make_jwt_rs256)

KEY = rsapkg.generate_keypair(1024)
OTHER_KEY = rsapkg.generate_keypair(1024)
JWKS = {"keys": [rsapkg.jwk_of(KEY["n"], KEY["e"], kid="k1")]}


def bearer(token):
    return {"Authorization": f"Bearer {token}"}


CLAIMS = {"iss": "https://issuer", "aud": "kube", "sub": "alice",
          "groups": ["dev"], "exp": time.time() + 600}


class TestRS256Verify:
    def test_sign_verify_roundtrip(self):
        msg = b"the quick brown fox"
        sig = rsapkg.sign_pkcs1v15_sha256(KEY["n"], KEY["d"], msg)
        assert rsapkg.verify_pkcs1v15_sha256(KEY["n"], KEY["e"], msg, sig)
        assert not rsapkg.verify_pkcs1v15_sha256(
            KEY["n"], KEY["e"], b"tampered", sig)
        assert not rsapkg.verify_pkcs1v15_sha256(
            OTHER_KEY["n"], OTHER_KEY["e"], msg, sig)

    def test_signature_length_and_range_checks(self):
        msg = b"m"
        sig = rsapkg.sign_pkcs1v15_sha256(KEY["n"], KEY["d"], msg)
        assert not rsapkg.verify_pkcs1v15_sha256(
            KEY["n"], KEY["e"], msg, sig[:-1])
        assert not rsapkg.verify_pkcs1v15_sha256(
            KEY["n"], KEY["e"], msg, sig + b"\x00")
        k = (KEY["n"].bit_length() + 7) // 8
        too_big = KEY["n"].to_bytes(k, "big")  # s >= n
        assert not rsapkg.verify_pkcs1v15_sha256(
            KEY["n"], KEY["e"], msg, too_big)

    def test_jwks_parsing_skips_malformed(self):
        jwks = {"keys": [
            {"kty": "EC", "crv": "P-256"},
            {"kty": "RSA"},                       # no n/e
            {"kty": "RSA", "n": "!!!", "e": "AQAB"},
            rsapkg.jwk_of(KEY["n"], KEY["e"], kid="good")]}
        keys = rsapkg.jwks_rsa_keys(jwks)
        assert len(keys) == 1 and keys[0][0] == "good"


class TestOIDCAuthenticator:
    def _auth(self, **kw):
        return JWTAuthenticator(issuer="https://issuer", audience="kube",
                                jwks=JWKS, **kw)

    def test_rs256_accept(self):
        token = make_jwt_rs256(KEY, CLAIMS, kid="k1")
        user, ok = self._auth().authenticate(bearer(token))
        assert ok and user.name == "alice" and user.groups == ["dev"]

    def test_rs256_wrong_key_rejected(self):
        token = make_jwt_rs256(OTHER_KEY, CLAIMS, kid="k1")
        _, ok = self._auth().authenticate(bearer(token))
        assert not ok

    def test_rs256_unknown_kid_still_verifies_by_key(self):
        # kid mismatch with a known key: token kid="other" finds no
        # candidate with that kid -> rejected (keys carry kids here)
        token = make_jwt_rs256(KEY, CLAIMS, kid="other")
        _, ok = self._auth().authenticate(bearer(token))
        assert not ok

    def test_rs256_no_kid_tries_all_keys(self):
        token = make_jwt_rs256(KEY, CLAIMS)
        _, ok = self._auth().authenticate(bearer(token))
        assert ok

    def test_alg_confusion_rs256_key_as_hs256_secret(self):
        """The classic downgrade: attacker signs HS256 using the PUBLIC
        key bytes as the HMAC secret. An RS256-only verifier must
        reject — it has no HS256 secret configured at all."""
        pub_bytes = KEY["n"].to_bytes(
            (KEY["n"].bit_length() + 7) // 8, "big")
        forged = make_jwt(pub_bytes, CLAIMS)
        _, ok = self._auth().authenticate(bearer(forged))
        assert not ok

    def test_alg_confusion_header_swap(self):
        """An RS256-signed token whose header claims HS256 must not
        verify via either path."""
        token = make_jwt_rs256(KEY, CLAIMS, kid="k1")
        head_b64, body, sig = token.split(".")
        import json
        head = json.loads(base64.urlsafe_b64decode(
            head_b64 + "=" * (-len(head_b64) % 4)))
        head["alg"] = "HS256"
        forged_head = base64.urlsafe_b64encode(
            json.dumps(head, separators=(",", ":")).encode()
        ).rstrip(b"=").decode()
        _, ok = self._auth().authenticate(
            bearer(f"{forged_head}.{body}.{sig}"))
        assert not ok

    def test_alg_none_rejected(self):
        import json
        head = base64.urlsafe_b64encode(
            json.dumps({"alg": "none"}).encode()).rstrip(b"=").decode()
        body = base64.urlsafe_b64encode(
            json.dumps(CLAIMS).encode()).rstrip(b"=").decode()
        _, ok = self._auth().authenticate(bearer(f"{head}.{body}."))
        assert not ok

    def test_hs256_still_works_alongside_jwks(self):
        auth = JWTAuthenticator(secret=b"s3cret", issuer="https://issuer",
                                audience="kube", jwks=JWKS)
        hs = make_jwt(b"s3cret", CLAIMS)
        rs = make_jwt_rs256(KEY, CLAIMS, kid="k1")
        assert auth.authenticate(bearer(hs))[1]
        assert auth.authenticate(bearer(rs))[1]

    def test_expired_rs256_rejected(self):
        token = make_jwt_rs256(
            KEY, {**CLAIMS, "exp": time.time() - 5}, kid="k1")
        _, ok = self._auth().authenticate(bearer(token))
        assert not ok


class TestMasterOIDC:
    def test_master_accepts_rs256_bearer(self):
        import urllib.request
        import urllib.error
        from kubernetes_tpu.master import Master, MasterConfig

        m = Master(MasterConfig(
            port=0, oidc_jwks=JWKS, oidc_issuer="https://issuer",
            oidc_client_id="kube")).start()
        try:
            token = make_jwt_rs256(KEY, CLAIMS, kid="k1")
            req = urllib.request.Request(
                m.url + "/api/v1/namespaces/default/pods",
                headers={"Authorization": f"Bearer {token}"})
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
            bad = urllib.request.Request(
                m.url + "/api/v1/namespaces/default/pods",
                headers={"Authorization": "Bearer bogus"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad)
            assert ei.value.code == 401
        finally:
            m.stop()


class TestKeystoneAuthenticator:
    """ref: plugin/pkg/auth/authenticator/request/keystone/keystone.go
    — basic-auth delegated to a keystone-v2-shaped endpoint."""

    def _mock_keystone(self):
        import json as jsonlib
        import threading
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = jsonlib.loads(self.rfile.read(n))
                creds = body.get("auth", {}).get(
                    "passwordCredentials", {})
                ok = (creds.get("username") == "alice"
                      and creds.get("password") == "horse-battery")
                payload = jsonlib.dumps(
                    {"access": {"token": {"id": "tok"}}}
                    if ok else {"error": {"code": 401}}).encode()
                self.send_response(200 if ok else 401)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        httpd.daemon_threads = True
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd

    def test_keystone_accept_and_reject(self):
        import base64 as b64

        from kubernetes_tpu.auth.authenticate import (
            KeystonePasswordAuthenticator)

        ks = self._mock_keystone()
        try:
            auth = KeystonePasswordAuthenticator(
                f"http://127.0.0.1:{ks.server_address[1]}/v2.0",
                allow_insecure_for_tests=True)

            def hdr(user, pw):
                raw = b64.b64encode(f"{user}:{pw}".encode()).decode()
                return {"Authorization": f"Basic {raw}"}

            user, ok = auth.authenticate(hdr("alice", "horse-battery"))
            assert ok and user.name == "alice"
            _, ok = auth.authenticate(hdr("alice", "wrong"))
            assert not ok
            _, ok = auth.authenticate({"Authorization": "Bearer x"})
            assert not ok
        finally:
            ks.shutdown()
            ks.server_close()

    def test_keystone_requires_https(self):
        import pytest as _pytest

        from kubernetes_tpu.auth.authenticate import (
            KeystonePasswordAuthenticator)

        with _pytest.raises(ValueError, match="https"):
            KeystonePasswordAuthenticator("http://keystone.example")
        KeystonePasswordAuthenticator("https://keystone.example")

    def test_keystone_unreachable_rejects(self):
        import base64 as b64

        from kubernetes_tpu.auth.authenticate import (
            KeystonePasswordAuthenticator)

        auth = KeystonePasswordAuthenticator(
            "http://127.0.0.1:9", timeout=0.5,
            allow_insecure_for_tests=True)
        raw = b64.b64encode(b"u:p").decode()
        _, ok = auth.authenticate({"Authorization": f"Basic {raw}"})
        assert not ok
