"""Service-discovery env vars, $(var) expansion, fieldRef env sources
(ref: pkg/kubelet/envvars/envvars.go + envvars_test.go,
third_party/golang/expansion/expand.go,
pkg/kubelet/kubelet.go:1340-1461)."""

import time

from kubernetes_tpu.api.client import InProcClient
from kubernetes_tpu.api.registry import Registry
from kubernetes_tpu.core import types as api
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes_tpu.kubelet.envvars import (expand, extract_field_path,
                                            from_services,
                                            make_environment,
                                            service_env_map)


def wait_until(cond, timeout=20.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def mksvc(name, cluster_ip, ports, namespace="default"):
    return api.Service(
        metadata=api.ObjectMeta(name=name, namespace=namespace),
        spec=api.ServiceSpec(cluster_ip=cluster_ip, ports=[
            api.ServicePort(name=n, port=p, protocol=proto)
            for n, p, proto in ports]))


class TestFromServices:
    def test_full_var_family(self):
        # mirrors envvars_test.go TestFromServices' zookeeper fixture
        svc = mksvc("zookeeper", "1.2.3.4",
                    [("", 2181, "TCP"), ("leader", 2888, "TCP")])
        got = {e.name: e.value for e in from_services([svc])}
        assert got == {
            "ZOOKEEPER_SERVICE_HOST": "1.2.3.4",
            "ZOOKEEPER_SERVICE_PORT": "2181",
            "ZOOKEEPER_SERVICE_PORT_LEADER": "2888",
            "ZOOKEEPER_PORT": "tcp://1.2.3.4:2181",
            "ZOOKEEPER_PORT_2181_TCP": "tcp://1.2.3.4:2181",
            "ZOOKEEPER_PORT_2181_TCP_PROTO": "tcp",
            "ZOOKEEPER_PORT_2181_TCP_PORT": "2181",
            "ZOOKEEPER_PORT_2181_TCP_ADDR": "1.2.3.4",
            "ZOOKEEPER_PORT_2888_TCP": "tcp://1.2.3.4:2888",
            "ZOOKEEPER_PORT_2888_TCP_PROTO": "tcp",
            "ZOOKEEPER_PORT_2888_TCP_PORT": "2888",
            "ZOOKEEPER_PORT_2888_TCP_ADDR": "1.2.3.4",
        }

    def test_dash_mangling_and_udp(self):
        svc = mksvc("simple-dns", "9.8.7.6", [("dns", 53, "UDP")])
        got = {e.name: e.value for e in from_services([svc])}
        assert got["SIMPLE_DNS_SERVICE_HOST"] == "9.8.7.6"
        assert got["SIMPLE_DNS_PORT"] == "udp://9.8.7.6:53"
        assert got["SIMPLE_DNS_PORT_53_UDP_PROTO"] == "udp"

    def test_headless_and_ipless_services_skipped(self):
        assert from_services([
            mksvc("headless", "None", [("", 80, "TCP")]),
            mksvc("pending", "", [("", 80, "TCP")])]) == []


class TestServiceEnvMap:
    def test_namespace_projection(self):
        services = [
            mksvc("db", "10.0.0.1", [("", 5432, "TCP")], namespace="prod"),
            mksvc("db", "10.0.0.2", [("", 5432, "TCP")], namespace="dev"),
            mksvc("kubernetes", "10.0.0.3", [("", 443, "TCP")],
                  namespace="default"),
            mksvc("other", "10.0.0.4", [("", 80, "TCP")],
                  namespace="default"),
        ]
        m = service_env_map(services, "prod")
        # own-namespace db, not dev's; master kubernetes service leaks
        # in from the master namespace; unrelated default services don't
        assert m["DB_SERVICE_HOST"] == "10.0.0.1"
        assert m["KUBERNETES_SERVICE_HOST"] == "10.0.0.3"
        assert "OTHER_SERVICE_HOST" not in m

    def test_pod_namespace_wins_name_collision(self):
        services = [
            mksvc("kubernetes", "10.0.0.3", [("", 443, "TCP")],
                  namespace="default"),
            mksvc("kubernetes", "10.9.9.9", [("", 443, "TCP")],
                  namespace="prod"),
        ]
        m = service_env_map(services, "prod")
        assert m["KUBERNETES_SERVICE_HOST"] == "10.9.9.9"


class TestExpansion:
    def test_cases(self):
        ctx = {"VAR_A": "A", "VAR_B": "B", "VAR_EMPTY": ""}
        cases = [
            ("$(VAR_A)", "A"),
            ("___$(VAR_B)___", "___B___"),
            ("$(VAR_A)$(VAR_B)", "AB"),
            ("$$(VAR_A)", "$(VAR_A)"),          # escaped operator
            ("$$$(VAR_A)", "$A"),               # escape then expand
            ("$(MISSING)", "$(MISSING)"),       # unresolved left intact
            ("$(VAR_EMPTY)", ""),
            ("$(incomplete", "$(incomplete"),
            ("trailing$", "trailing$"),
            ("$x", "$x"),
            ("()", "()"),
        ]
        for value, want in cases:
            assert expand(value, ctx) == want, value

    def test_earlier_map_shadows_later(self):
        assert expand("$(X)", {"X": "first"}, {"X": "second"}) == "first"


class TestFieldPath:
    def test_paths(self):
        pod = api.Pod(metadata=api.ObjectMeta(
            name="p", namespace="ns", labels={"a": "1", "b": "2"},
            annotations={"k": "v"}),
            status=api.PodStatus(pod_ip="10.1.2.3"))
        assert extract_field_path(pod, "metadata.name") == "p"
        assert extract_field_path(pod, "metadata.namespace") == "ns"
        assert extract_field_path(pod, "status.podIP") == "10.1.2.3"
        assert extract_field_path(pod, "metadata.labels") == \
            'a="1"\nb="2"\n'
        assert extract_field_path(pod, "metadata.annotations") == 'k="v"\n'

    def test_quotes_and_newlines_escaped(self):
        # a quote/newline in an annotation value must not forge extra
        # key=value lines (fieldpath.go formatMap %q)
        pod = api.Pod(metadata=api.ObjectMeta(
            annotations={"a": 'x"y', "b": "l1\nl2"}))
        got = extract_field_path(pod, "metadata.annotations")
        assert got == 'a="x\\"y"\nb="l1\\nl2"\n'


class TestMakeEnvironment:
    def _pod(self, env):
        return api.Pod(
            metadata=api.ObjectMeta(name="p", namespace="default",
                                    uid="u1"),
            spec=api.PodSpec(node_name="n1", containers=[
                api.Container(name="c", image="i", env=env)]),
            status=api.PodStatus(pod_ip="10.1.1.1"))

    def test_declared_order_expansion_and_service_tail(self):
        svc = mksvc("db", "10.0.0.1", [("", 5432, "TCP")])
        pod = self._pod([
            api.EnvVar(name="A", value="a"),
            api.EnvVar(name="B", value="$(A)-$(DB_SERVICE_HOST)"),
        ])
        env = make_environment(pod, pod.spec.containers[0], [svc])
        names = [e.name for e in env]
        # declared vars first, in declaration order; service vars after
        assert names[:2] == ["A", "B"]
        byname = {e.name: e.value for e in env}
        assert byname["B"] == "a-10.0.0.1"
        assert byname["DB_SERVICE_HOST"] == "10.0.0.1"

    def test_declared_var_shadows_service_var(self):
        svc = mksvc("db", "10.0.0.1", [("", 5432, "TCP")])
        pod = self._pod([api.EnvVar(name="DB_SERVICE_HOST",
                                    value="override")])
        env = make_environment(pod, pod.spec.containers[0], [svc])
        assert [e.value for e in env if e.name == "DB_SERVICE_HOST"] == \
            ["override"]

    def test_field_ref_source(self):
        pod = self._pod([api.EnvVar(
            name="MY_POD_IP",
            value_from=api.EnvVarSource(field_ref=api.ObjectFieldSelector(
                field_path="status.podIP")))])
        env = make_environment(pod, pod.spec.containers[0], [])
        assert env == [api.EnvVar(name="MY_POD_IP", value="10.1.1.1")]


class TestKubeletServiceEnv:
    def test_started_container_gets_service_and_fieldref_env(self):
        registry = Registry()
        client = InProcClient(registry)
        started = {}

        class RecordingRuntime(FakeRuntime):
            def start_container(self, pod, container):
                started[container.name] = list(container.env)
                return super().start_container(pod, container)

        client.create("services", mksvc(
            "redis-master", "10.0.0.11", [("", 6379, "TCP")]), "default")
        kubelet = Kubelet(client, "n1", runtime=RecordingRuntime()).run()
        try:
            assert wait_until(
                lambda: kubelet._service_informer.has_synced)
            pod = api.Pod(
                metadata=api.ObjectMeta(name="web", namespace="default",
                                        uid="u-env"),
                spec=api.PodSpec(node_name="n1", containers=[
                    api.Container(name="c", image="i", env=[
                        api.EnvVar(name="WHOAMI",
                                   value_from=api.EnvVarSource(
                                       field_ref=api.ObjectFieldSelector(
                                           field_path="metadata.name"))),
                        api.EnvVar(name="REDIS",
                                   value="$(REDIS_MASTER_SERVICE_HOST)"),
                    ])]),
                status=api.PodStatus(phase="Pending"))
            client.create("pods", pod, "default")
            assert wait_until(lambda: "c" in started)
            env = {e.name: e.value for e in started["c"]}
            assert env["WHOAMI"] == "web"
            assert env["REDIS"] == "10.0.0.11"
            assert env["REDIS_MASTER_SERVICE_HOST"] == "10.0.0.11"
            assert env["REDIS_MASTER_PORT"] == "tcp://10.0.0.11:6379"
        finally:
            kubelet.stop()
