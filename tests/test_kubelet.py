"""Kubelet core: PLEG diffing, probers, restart policies, pod phase,
housekeeping (ref: pkg/kubelet — pleg/generic.go, prober/, kubelet.go
syncPod/getPhase/HandlePodCleanups)."""

import time

import pytest

from kubernetes_tpu.api.client import InProcClient
from kubernetes_tpu.api.registry import Registry
from kubernetes_tpu.core import types as api
from kubernetes_tpu.kubelet import (FakeRuntime, GenericPLEG, Kubelet,
                                    Prober, ProberManager)
from kubernetes_tpu.kubelet.pleg import (CONTAINER_DIED, CONTAINER_REMOVED,
                                         CONTAINER_STARTED)


def wait_until(cond, timeout=20.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def mkpod(name, uid, restart_policy="Always", containers=None, node="n1"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default", uid=uid),
        spec=api.PodSpec(
            node_name=node, restart_policy=restart_policy,
            containers=containers or [api.Container(name="c", image="img")]),
        status=api.PodStatus(phase="Pending"))


class TestPLEG:
    def test_diff_events(self):
        runtime = FakeRuntime()
        pleg = GenericPLEG(runtime)
        pod = mkpod("p", "uid-1")
        runtime.start_container(pod, pod.spec.containers[0])
        assert pleg.relist() == 1
        ev = pleg.events.get_nowait()
        assert ev.type == CONTAINER_STARTED and ev.pod_uid == "uid-1"

        runtime.exit_container("uid-1", "c")
        assert pleg.relist() == 1
        assert pleg.events.get_nowait().type == CONTAINER_DIED

        runtime.kill_pod("uid-1")
        assert pleg.relist() == 1
        assert pleg.events.get_nowait().type == CONTAINER_REMOVED

        assert pleg.relist() == 0  # steady state is quiet


class TestProber:
    def test_exec_probe_via_runner(self):
        outcomes = {"ok": True}
        prober = Prober(exec_runner=lambda pod, c, cmd:
                        (outcomes["ok"], "out"))
        probe = api.Probe(exec=api.ExecAction(command=["check"]))
        pod = mkpod("p", "u1")
        assert prober.probe(probe, pod, pod.spec.containers[0],
                            "").result == "success"
        outcomes["ok"] = False
        assert prober.probe(probe, pod, pod.spec.containers[0],
                            "").result == "failure"

    def test_tcp_probe_against_live_socket(self):
        import socket as pysocket
        srv = pysocket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        try:
            prober = Prober()
            probe = api.Probe(tcp_socket=api.TCPSocketAction(port=port))
            pod = mkpod("p", "u1")
            assert prober.probe(probe, pod, pod.spec.containers[0],
                                "127.0.0.1").result == "success"
            probe_bad = api.Probe(tcp_socket=api.TCPSocketAction(port=1))
            assert prober.probe(probe_bad, pod, pod.spec.containers[0],
                                "127.0.0.1").result == "failure"
        finally:
            srv.close()

    def test_manager_liveness_failure_callback(self):
        failures = []
        manager = ProberManager(
            Prober(exec_runner=lambda pod, c, cmd: (False, "dead")),
            on_liveness_failure=lambda pod, name, msg:
            failures.append(name))
        probe = api.Probe(exec=api.ExecAction(command=["x"]),
                          period_seconds=0, failure_threshold=2)
        pod = mkpod("p", "u1", containers=[api.Container(
            name="c", image="i", liveness_probe=probe)])
        manager.add_pod(pod)
        try:
            assert wait_until(lambda: failures == ["c"], timeout=10)
        finally:
            manager.stop()


@pytest.fixture()
def kubelet_env():
    registry = Registry()
    client = InProcClient(registry)
    runtime = FakeRuntime()
    kubelet = Kubelet(client, "n1", runtime=runtime).run()
    yield registry, client, runtime, kubelet
    kubelet.stop()


def bound_pod(client, name, uid, restart_policy="Always", containers=None):
    pod = mkpod(name, uid, restart_policy, containers)
    return client.create("pods", pod, "default")


class TestKubeletSync:
    def test_pod_runs_and_reports_running(self, kubelet_env):
        registry, client, runtime, kubelet = kubelet_env
        bound_pod(client, "web", "u-web")
        assert wait_until(lambda: client.get(
            "pods", "web", "default").status.phase == "Running")
        pod = client.get("pods", "web", "default")
        assert pod.status.container_statuses[0].ready
        assert runtime.running_containers(pod.metadata.uid) == ["c"]

    def test_always_restarts_crashed_container(self, kubelet_env):
        registry, client, runtime, kubelet = kubelet_env
        created = bound_pod(client, "web", "u-web")
        assert wait_until(
            lambda: runtime.running_containers(created.metadata.uid))
        runtime.exit_container(created.metadata.uid, "c", exit_code=1)
        assert wait_until(lambda: client.get(
            "pods", "web",
            "default").status.container_statuses[0].restart_count >= 1)
        assert wait_until(lambda: client.get(
            "pods", "web", "default").status.phase == "Running")

    def test_never_policy_reports_failed(self, kubelet_env):
        registry, client, runtime, kubelet = kubelet_env
        created = bound_pod(client, "once", "u-once",
                            restart_policy="Never")
        assert wait_until(
            lambda: runtime.running_containers(created.metadata.uid))
        runtime.exit_container(created.metadata.uid, "c", exit_code=2)
        assert wait_until(lambda: client.get(
            "pods", "once", "default").status.phase == "Failed")
        # and stays dead
        time.sleep(0.3)
        assert runtime.running_containers(created.metadata.uid) == []

    def test_onfailure_policy_succeeds_on_zero_exit(self, kubelet_env):
        registry, client, runtime, kubelet = kubelet_env
        created = bound_pod(client, "batch", "u-batch",
                            restart_policy="OnFailure")
        assert wait_until(
            lambda: runtime.running_containers(created.metadata.uid))
        runtime.exit_container(created.metadata.uid, "c", exit_code=0)
        assert wait_until(lambda: client.get(
            "pods", "batch", "default").status.phase == "Succeeded")

    def test_deleted_pod_reaped_by_housekeeping(self, kubelet_env):
        registry, client, runtime, kubelet = kubelet_env
        created = bound_pod(client, "gone", "u-gone")
        assert wait_until(
            lambda: runtime.running_containers(created.metadata.uid))
        client.delete("pods", "gone", "default")
        assert wait_until(
            lambda: runtime.running_containers("u-gone") == [], timeout=10)

    def test_terminating_pod_update_never_resurrects(self, kubelet_env):
        """Any event on a pod with deletionTimestamp set is terminating
        (the reference's syncPod checks DeletionTimestamp): a re-stamp
        (second delete with shorter grace) or PUT to a marked pod must
        not re-add it to the worker set or restart its containers, and
        re-entrant teardowns dedupe on _tearing_down."""
        import dataclasses
        registry, client, runtime, kubelet = kubelet_env
        created = bound_pod(client, "doomed", "u-doom")
        assert wait_until(
            lambda: runtime.running_containers("u-doom"))

        def marked(base, grace):
            return dataclasses.replace(base, metadata=dataclasses.replace(
                base.metadata, deletion_timestamp="2099-01-01T00:00:00Z",
                deletion_grace_period_seconds=grace))

        kubelet.handle_pod_update(created, marked(created, 30))
        assert wait_until(
            lambda: runtime.running_containers("u-doom") == [])
        # a second delete re-stamps a shorter grace: MODIFIED on an
        # already-marked pod — must not resurrect
        kubelet.handle_pod_update(marked(created, 30), marked(created, 5))
        # a racing worker sync on the marked pod must not start anything
        kubelet.sync_pod(marked(created, 5))
        time.sleep(0.2)
        assert runtime.running_containers("u-doom") == []
        assert "u-doom" not in kubelet._pods

    def test_sync_pod_skips_terminating(self, kubelet_env):
        """sync_pod bails before any setup/start for a marked pod."""
        import dataclasses
        registry, client, runtime, kubelet = kubelet_env
        pod = mkpod("ghost", "u-ghost")
        pod = dataclasses.replace(pod, metadata=dataclasses.replace(
            pod.metadata, deletion_timestamp="2099-01-01T00:00:00Z"))
        kubelet.sync_pod(pod)
        assert runtime.running_containers("u-ghost") == []

    def test_liveness_failure_restarts(self, kubelet_env):
        registry, client, runtime, kubelet = kubelet_env
        health = {"ok": True}
        kubelet.prober_manager.prober = Prober(
            exec_runner=lambda pod, c, cmd: (health["ok"], ""))
        probe = api.Probe(exec=api.ExecAction(command=["hc"]),
                          period_seconds=0, failure_threshold=1)
        created = bound_pod(client, "flaky", "u-flaky", containers=[
            api.Container(name="c", image="i", liveness_probe=probe)])
        assert wait_until(
            lambda: runtime.running_containers(created.metadata.uid))
        health["ok"] = False
        assert wait_until(lambda: client.get(
            "pods", "flaky",
            "default").status.container_statuses[0].restart_count >= 1,
            timeout=15)
        health["ok"] = True
        assert wait_until(lambda: client.get(
            "pods", "flaky", "default").status.phase == "Running")

    def test_readiness_gates_ready_condition(self, kubelet_env):
        registry, client, runtime, kubelet = kubelet_env
        ready = {"ok": False}
        kubelet.prober_manager.prober = Prober(
            exec_runner=lambda pod, c, cmd: (ready["ok"], ""))
        probe = api.Probe(exec=api.ExecAction(command=["rc"]),
                          period_seconds=0, failure_threshold=1)
        created = bound_pod(client, "warm", "u-warm", containers=[
            api.Container(name="c", image="i", readiness_probe=probe)])
        assert wait_until(lambda: client.get(
            "pods", "warm", "default").status.phase == "Running")

        def ready_cond():
            pod = client.get("pods", "warm", "default")
            return next((c.status for c in pod.status.conditions
                         if c.type == "Ready"), None)
        assert wait_until(lambda: ready_cond() == "False")
        ready["ok"] = True
        assert wait_until(lambda: ready_cond() == "True", timeout=15)


class TestSpecDrift:
    """syncPod must make running containers MATCH the spec — divergent
    containers restart at the new spec and removed containers are
    killed (the reference's dockertools container hash, manager.go
    HashContainer/SyncPod; kubelet.go:1597)."""

    def test_image_change_restarts_running_container(self, kubelet_env):
        registry, client, runtime, kubelet = kubelet_env
        created = bound_pod(client, "web", "u-web")
        assert wait_until(
            lambda: runtime.running_containers(created.metadata.uid))
        live = client.get("pods", "web", "default")
        live.spec.containers[0].image = "img:v2"
        client.update("pods", live, "default")
        # the container restarts onto the new image
        from kubernetes_tpu.kubelet.container import ContainerState

        def new_image_running():
            for rp in runtime.get_pods():
                if rp.uid != created.metadata.uid:
                    continue
                return any(c.name == "c" and c.image == "img:v2"
                           and c.state == ContainerState.RUNNING
                           for c in rp.containers)
            return False
        assert wait_until(new_image_running, timeout=15)

    def test_container_removed_from_spec_is_killed(self, kubelet_env):
        registry, client, runtime, kubelet = kubelet_env
        created = bound_pod(client, "web", "u-web", containers=[
            api.Container(name="a", image="img"),
            api.Container(name="b", image="img")])
        assert wait_until(lambda: sorted(
            runtime.running_containers(created.metadata.uid)) == ["a", "b"])
        live = client.get("pods", "web", "default")
        live.spec.containers = [c for c in live.spec.containers
                                if c.name == "a"]
        client.update("pods", live, "default")
        assert wait_until(lambda: runtime.running_containers(
            created.metadata.uid) == ["a"], timeout=15)


def test_image_pull_policy_never_present_does_not_pull():
    """PullNever never invokes the puller, present or not — the
    reference's shouldPullImage is unconditionally false for PullNever
    (image_puller.go); absent is a start error, present is a no-op."""
    from kubernetes_tpu.kubelet.images import ImageManager, \
        ImageNeverPullError

    pulls = []
    mgr = ImageManager(puller=pulls.append)
    pod = mkpod("p", "u1")
    cont = api.Container(name="c", image="present-img",
                        image_pull_policy="Never")
    with pytest.raises(ImageNeverPullError):
        mgr.ensure_image_exists(pod, cont)
    assert pulls == []
    mgr.mark_present("present-img") if hasattr(mgr, "mark_present") else \
        mgr._present.update({"present-img": 1.0})
    mgr.ensure_image_exists(pod, cont)
    assert pulls == []
