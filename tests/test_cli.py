"""kubectl CLI against the in-proc client (ref: pkg/kubectl/cmd tests use
canned clients; the command surface mirrors cmd.go:134)."""

import io
import json

import pytest

from kubernetes_tpu.api.client import InProcClient
from kubernetes_tpu.api.registry import Registry
from kubernetes_tpu.cli.cmd import main
from kubernetes_tpu.core import types as api
from kubernetes_tpu.core.quantity import parse_quantity


@pytest.fixture()
def cluster():
    registry = Registry()
    client = InProcClient(registry)
    return registry, client


def run_cli(client, *argv):
    out = io.StringIO()
    err = io.StringIO()
    code = main(list(argv), client=client, out=out, err=err)
    return code, out.getvalue(), err.getvalue()


def mkpod(name, labels=None, phase="Running", node="n1"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default",
                                labels=labels or {}),
        spec=api.PodSpec(node_name=node, containers=[api.Container(
            name="c", image="img")]),
        status=api.PodStatus(
            phase=phase,
            container_statuses=[api.ContainerStatus(
                name="c", ready=(phase == "Running"),
                state=api.ContainerState(
                    running=api.ContainerStateRunning()))]))


class TestGet:
    def test_table_output(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("web-1", {"app": "web"}), "default")
        client.create("pods", mkpod("web-2", {"app": "web"},
                                    phase="Pending"), "default")
        code, out, _ = run_cli(client, "get", "pods")
        assert code == 0
        lines = out.splitlines()
        assert lines[0].split() == ["NAME", "READY", "STATUS", "RESTARTS",
                                    "AGE"]
        assert "web-1" in out and "Running" in out
        assert "web-2" in out and "Pending" in out

    def test_aliases_and_selector(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("a", {"app": "x"}), "default")
        client.create("pods", mkpod("b", {"app": "y"}), "default")
        code, out, _ = run_cli(client, "get", "po", "-l", "app=x")
        assert code == 0
        assert "a" in out and "b" not in out

    def test_json_and_jsonpath(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("web"), "default")
        code, out, _ = run_cli(client, "get", "pod/web", "-o", "json")
        data = json.loads(out)
        assert data["metadata"]["name"] == "web"
        code, out, _ = run_cli(client, "get", "pod/web", "-o",
                               "jsonpath={.spec.nodeName}")
        assert out.strip() == "n1"

    def test_custom_columns(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("web"), "default")
        client.create("pods", mkpod("db"), "default")
        code, out, _ = run_cli(
            client, "get", "pods", "-o",
            "custom-columns=NAME:.metadata.name,NODE:.spec.nodeName,"
            "MISSING:.status.podIP")
        lines = out.splitlines()
        assert lines[0].split() == ["NAME", "NODE", "MISSING"]
        body = {tuple(ln.split()) for ln in lines[1:]}
        # unset fields print <none> (custom_column_printer.go)
        assert body == {("web", "n1", "<none>"),
                        ("db", "n1", "<none>")}
        # malformed column spec is an error, not a silent table
        code, _, err = run_cli(client, "get", "pods", "-o",
                               "custom-columns=NAMEONLY")
        assert code != 0

    def test_output_name(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w"), "default")
        code, out, _ = run_cli(client, "get", "pods", "-o", "name")
        assert out.strip() == "pods/w"

    def test_mixed_kinds_print_stacked_tables(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w"), "default")
        client.create("services", api.Service(
            metadata=api.ObjectMeta(name="svc1", namespace="default"),
            spec=api.ServiceSpec(selector={"app": "web"},
                                 ports=[api.ServicePort(port=80)])),
            "default")
        code, out, err = run_cli(client, "get", "pods,svc")
        assert code == 0, err
        assert "STATUS" in out and "CLUSTER_IP" in out
        assert "w" in out and "svc1" in out

    def test_get_missing_is_error(self, cluster):
        _, client = cluster
        code, out, err = run_cli(client, "get", "pod/nope")
        assert code == 1
        assert "Error" in err


class TestCreateApplyDelete:
    def test_create_from_file(self, cluster, tmp_path):
        _, client = cluster
        manifest = tmp_path / "pod.json"
        manifest.write_text(json.dumps({
            "kind": "Pod", "apiVersion": "v1",
            "metadata": {"name": "filed", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "img"}]}}))
        code, out, _ = run_cli(client, "create", "-f", str(manifest))
        assert code == 0 and "pods/filed created" in out
        assert client.get("pods", "filed", "default")

    def test_apply_updates(self, cluster, tmp_path):
        _, client = cluster
        doc = {"kind": "ReplicationController", "apiVersion": "v1",
               "metadata": {"name": "rc1", "namespace": "default"},
               "spec": {"replicas": 1, "selector": {"a": "b"},
                        "template": {"metadata": {"labels": {"a": "b"}},
                                     "spec": {"containers": [
                                         {"name": "c", "image": "i"}]}}}}
        manifest = tmp_path / "rc.json"
        manifest.write_text(json.dumps(doc))
        code, out, _ = run_cli(client, "apply", "-f", str(manifest))
        assert "created" in out
        doc["spec"]["replicas"] = 4
        manifest.write_text(json.dumps(doc))
        code, out, _ = run_cli(client, "apply", "-f", str(manifest))
        assert "configured" in out
        assert client.get("replicationcontrollers", "rc1",
                          "default").spec.replicas == 4

    def test_delete_by_selector(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("a", {"app": "x"}), "default")
        client.create("pods", mkpod("b", {"app": "y"}), "default")
        code, out, _ = run_cli(client, "delete", "pods", "-l", "app=x")
        assert code == 0 and "pods/a deleted" in out
        assert len(client.list("pods", "default")[0]) == 1

    def test_delete_grace_period_flag(self, cluster):
        """--grace-period (delete.go:98): a positive value runs the
        graceful two-phase; 0 forces; negative (default) uses the
        pod's own spec grace."""
        _, client = cluster
        pod = mkpod("g", {"app": "g"})
        pod.spec.termination_grace_period_seconds = 30
        client.create("pods", pod, "default")
        code, out, _ = run_cli(client, "delete", "pods", "g",
                               "--grace-period", "10")
        assert code == 0 and "pods/g deleted" in out
        marked = client.get("pods", "g", "default")
        assert marked.metadata.deletion_grace_period_seconds == 10
        code, _, _ = run_cli(client, "delete", "pods", "g",
                             "--grace-period", "0")
        assert code == 0
        assert all(p.metadata.name != "g"
                   for p in client.list("pods", "default")[0])


class TestMutations:
    def rc(self, client, replicas=2):
        return client.create("replicationcontrollers",
                             api.ReplicationController(
                                 metadata=api.ObjectMeta(
                                     name="web", namespace="default"),
                                 spec=api.ReplicationControllerSpec(
                                     replicas=replicas,
                                     selector={"app": "web"},
                                     template=api.PodTemplateSpec(
                                         metadata=api.ObjectMeta(
                                             labels={"app": "web"}),
                                         spec=api.PodSpec(containers=[
                                             api.Container(
                                                 name="c", image="i")])))),
                             "default")

    def test_scale(self, cluster):
        _, client = cluster
        self.rc(client)
        code, out, _ = run_cli(client, "scale", "rc", "web",
                               "--replicas", "5")
        assert code == 0
        assert client.get("replicationcontrollers", "web",
                          "default").spec.replicas == 5

    def test_scale_precondition(self, cluster):
        _, client = cluster
        self.rc(client, replicas=2)
        code, _, err = run_cli(client, "scale", "rc", "web",
                               "--replicas", "5",
                               "--current-replicas", "3")
        assert code == 1 and "precondition" in err

    def test_label_and_annotate(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w"), "default")
        code, _, _ = run_cli(client, "label", "pod", "w", "tier=frontend")
        assert code == 0
        assert client.get("pods", "w",
                          "default").metadata.labels["tier"] == "frontend"
        # no overwrite without the flag
        code, _, err = run_cli(client, "label", "pod", "w", "tier=backend")
        assert code == 1 and "--overwrite" in err
        code, _, _ = run_cli(client, "label", "pod", "w", "tier=backend",
                             "--overwrite")
        assert client.get("pods", "w",
                          "default").metadata.labels["tier"] == "backend"
        # removal via trailing dash
        run_cli(client, "label", "pod", "w", "tier-")
        assert "tier" not in client.get("pods", "w",
                                        "default").metadata.labels
        run_cli(client, "annotate", "pod", "w", "note=hello")
        assert client.get("pods", "w",
                          "default").metadata.annotations["note"] == "hello"
        # removal-only in TYPE/NAME form
        run_cli(client, "label", "pod", "w", "extra=1")
        code, _, err = run_cli(client, "label", "pod/w", "extra-")
        assert code == 0, err
        assert "extra" not in client.get("pods", "w",
                                         "default").metadata.labels

    def test_run_rejects_malformed_labels(self, cluster):
        _, client = cluster
        code, _, err = run_cli(client, "run", "w", "--image", "i",
                               "-l", "foo")
        assert code == 1 and "label" in err
        # no RC with a match-everything selector got created
        assert client.list("replicationcontrollers", "default")[0] == []

    def test_expose_and_autoscale_and_run(self, cluster):
        _, client = cluster
        self.rc(client)
        code, out, _ = run_cli(client, "expose", "rc", "web",
                               "--port", "80")
        assert code == 0
        svc = client.get("services", "web", "default")
        assert svc.spec.selector == {"app": "web"}
        assert svc.spec.cluster_ip.startswith("10.0.0.")

        code, _, _ = run_cli(client, "autoscale", "rc", "web",
                             "--max", "10", "--cpu-percent", "50")
        hpa = client.get("horizontalpodautoscalers", "web", "default")
        assert hpa.spec.max_replicas == 10

        code, _, _ = run_cli(client, "run", "worker", "--image", "img:w",
                             "-r", "3")
        rc = client.get("replicationcontrollers", "worker", "default")
        assert rc.spec.replicas == 3
        assert rc.spec.template.spec.containers[0].image == "img:w"

    def test_rolling_update(self, cluster):
        _, client = cluster
        self.rc(client, replicas=3)
        code, out, _ = run_cli(client, "rolling-update", "web", "web-v2",
                               "--image", "img:v2")
        assert code == 0
        rcs, _ = client.list("replicationcontrollers", "default")
        assert len(rcs) == 1
        assert rcs[0].metadata.name == "web-v2"
        assert rcs[0].spec.replicas == 3
        assert rcs[0].spec.template.spec.containers[0].image == "img:v2"

    def test_rolling_update_with_live_rc_manager(self, cluster):
        # the old RC must not adopt (and then delete) the new RC's pods:
        # the updater disjoints the old selector first
        import time
        from kubernetes_tpu.controllers import ReplicationManager
        _, client = cluster
        self.rc(client, replicas=2)
        mgr = ReplicationManager(client).run()
        try:
            deadline = time.time() + 30
            while time.time() < deadline and len(
                    client.list("pods", "default")[0]) < 2:
                time.sleep(0.05)
            code, out, _ = run_cli(client, "rolling-update", "web",
                                   "web-v2", "--image", "img:v2")
            assert code == 0
            deadline = time.time() + 90  # generous: suite runs under load
            def settled():
                pods = client.list("pods", "default")[0]
                return (len(pods) == 2 and all(
                    p.metadata.labels.get("deployment") == "web-v2"
                    for p in pods))
            while time.time() < deadline and not settled():
                time.sleep(0.1)
            assert settled(), [
                (p.metadata.name, p.metadata.labels)
                for p in client.list("pods", "default")[0]]
        finally:
            mgr.stop()


class TestDescribeAndMisc:
    def test_describe_pod(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w", {"app": "web"}), "default")
        code, out, _ = run_cli(client, "describe", "pod", "w")
        assert code == 0
        assert "Name:\tw" in out and "Image:\timg" in out

    def test_version_and_api_versions(self, cluster):
        _, client = cluster
        code, out, _ = run_cli(client, "version")
        assert "Client Version" in out
        code, out, _ = run_cli(client, "api-versions")
        assert "v1" in out and "extensions/v1beta1" in out

    def test_logs_hollow(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w"), "default")
        code, out, _ = run_cli(client, "logs", "w")
        assert code == 0 and "state=running" in out


class TestV11CommandParity:
    """replace / patch / stop / edit / explain / convert / proxy /
    namespace (ref: cmd.go:151-183's full v1.1 command tree)."""

    def _manifest(self, tmp_path, obj_dict):
        p = tmp_path / "m.json"
        p.write_text(json.dumps(obj_dict))
        return str(p)

    def test_replace_updates_from_file(self, cluster, tmp_path):
        _, client = cluster
        client.create("pods", mkpod("web"), "default")
        path = self._manifest(tmp_path, {
            "kind": "Pod", "apiVersion": "v1",
            "metadata": {"name": "web", "namespace": "default",
                         "labels": {"tier": "prod"}},
            "spec": {"nodeName": "n1",
                     "containers": [{"name": "c", "image": "img:v2"}]}})
        code, out, _ = run_cli(client, "replace", "-f", path)
        assert code == 0 and "replaced" in out
        live = client.get("pods", "web", "default")
        assert live.spec.containers[0].image == "img:v2"
        assert live.metadata.labels == {"tier": "prod"}

    def test_replace_force_recreates(self, cluster, tmp_path):
        _, client = cluster
        client.create("pods", mkpod("web"), "default")
        old_uid = client.get("pods", "web", "default").metadata.uid
        path = self._manifest(tmp_path, {
            "kind": "Pod", "apiVersion": "v1",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "img"}]}})
        code, out, _ = run_cli(client, "replace", "-f", path, "--force")
        assert code == 0 and "forced" in out
        assert client.get("pods", "web", "default").metadata.uid != old_uid

    def test_patch_strategic_merge(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("web", labels={"app": "x"}), "default")
        code, out, _ = run_cli(
            client, "patch", "pod", "web", "-p",
            '{"metadata": {"labels": {"extra": "y"}}}')
        assert code == 0 and "patched" in out
        live = client.get("pods", "web", "default")
        # strategic merge: existing labels survive, the patch adds
        assert live.metadata.labels == {"app": "x", "extra": "y"}

    def test_patch_merges_container_list_by_name(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("web"), "default")
        code, _, _ = run_cli(
            client, "patch", "pod", "web", "-p",
            '{"spec": {"containers": [{"name": "c", "image": "img:v3"}]}}')
        assert code == 0
        live = client.get("pods", "web", "default")
        assert len(live.spec.containers) == 1
        assert live.spec.containers[0].image == "img:v3"

    def test_patch_null_deletes_key(self, cluster):
        """Strategic-merge: an explicit null removes the key entirely
        (patch.go), it must not survive as a None value."""
        _, client = cluster
        client.create("pods", mkpod("web", labels={"app": "x",
                                                   "extra": "y"}),
                      "default")
        code, _, _ = run_cli(
            client, "patch", "pod", "web", "-p",
            '{"metadata": {"labels": {"extra": null}}}')
        assert code == 0
        live = client.get("pods", "web", "default")
        assert live.metadata.labels == {"app": "x"}

    def test_stop_waits_for_live_manager_scale_down(self, cluster):
        """With a running ReplicationManager, stop must not orphan the
        RC's pods: the reaper waits for observed replicas==0 before
        deleting (pkg/kubectl/stop.go)."""
        from kubernetes_tpu.controllers.replication import (
            ReplicationManager)
        _, client = cluster
        client.create("replicationcontrollers", api.ReplicationController(
            metadata=api.ObjectMeta(name="rcl", namespace="default",
                                    labels={"app": "live"}),
            spec=api.ReplicationControllerSpec(
                replicas=2, selector={"app": "live"},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"app": "live"}),
                    spec=api.PodSpec(containers=[api.Container(
                        name="c", image="i")])))), "default")
        mgr = ReplicationManager(client).run()
        try:
            import time
            deadline = time.time() + 15
            while time.time() < deadline:
                pods, _ = client.list("pods", "default",
                                      label_selector="app=live")
                if len(pods) == 2:
                    break
                time.sleep(0.05)
            code, out, _ = run_cli(client, "stop", "rc", "rcl")
            assert code == 0 and "stopped" in out
            deadline = time.time() + 15
            while time.time() < deadline:
                pods, _ = client.list("pods", "default",
                                      label_selector="app=live")
                if not pods:
                    break
                time.sleep(0.05)
            assert not pods, f"orphaned pods: {[p.metadata.name for p in pods]}"
        finally:
            mgr.stop()

    def test_stop_scales_rc_to_zero_then_deletes(self, cluster):
        registry, client = cluster
        client.create("replicationcontrollers", api.ReplicationController(
            metadata=api.ObjectMeta(name="rc1", namespace="default"),
            spec=api.ReplicationControllerSpec(
                replicas=3, selector={"app": "w"})), "default")
        seen = []
        w = client.watch("replicationcontrollers", "default")
        code, out, _ = run_cli(client, "stop", "rc", "rc1")
        assert code == 0 and "stopped" in out
        while True:
            ev = w.next(timeout=1)
            if ev is None:
                break
            seen.append((ev.type, ev.object.spec.replicas))
        w.stop()
        # the scale-to-0 write lands before the delete (the reaper order)
        assert ("MODIFIED", 0) in seen
        assert seen[-1][0] == "DELETED"
        from kubernetes_tpu.core.errors import NotFound as NF
        with pytest.raises(NF):
            client.get("replicationcontrollers", "rc1", "default")

    def test_delete_rc_cascades_by_default(self, cluster):
        """kubectl delete rc reaps (scale to 0, wait, delete) unless
        --cascade=false (ref: delete.go:97,140 ReapResult)."""
        registry, client = cluster
        client.create("replicationcontrollers", api.ReplicationController(
            metadata=api.ObjectMeta(name="rc1", namespace="default"),
            spec=api.ReplicationControllerSpec(
                replicas=3, selector={"app": "w"})), "default")
        seen = []
        w = client.watch("replicationcontrollers", "default")
        code, out, _ = run_cli(client, "delete", "rc", "rc1")
        assert code == 0 and "deleted" in out
        while True:
            ev = w.next(timeout=1)
            if ev is None:
                break
            seen.append((ev.type, ev.object.spec.replicas))
        w.stop()
        assert ("MODIFIED", 0) in seen  # the reaper's scale-to-0 write
        assert seen[-1][0] == "DELETED"

    def test_delete_rc_no_cascade_skips_reap(self, cluster):
        registry, client = cluster
        client.create("replicationcontrollers", api.ReplicationController(
            metadata=api.ObjectMeta(name="rc1", namespace="default"),
            spec=api.ReplicationControllerSpec(
                replicas=3, selector={"app": "w"})), "default")
        seen = []
        w = client.watch("replicationcontrollers", "default")
        code, _, _ = run_cli(client, "delete", "rc", "rc1",
                             "--cascade", "false")
        assert code == 0
        while True:
            ev = w.next(timeout=1)
            if ev is None:
                break
            seen.append((ev.type, ev.object.spec.replicas))
        w.stop()
        # straight delete: no scale-to-0 write ever lands
        assert all(t != "MODIFIED" for t, _r in seen)
        assert seen[-1] == ("DELETED", 3)

    def test_delete_job_reaps_pods(self, cluster):
        """JobReaper.Stop: parallelism to 0, dead pods removed, then
        the job itself."""
        registry, client = cluster
        client.create("jobs", api.Job(
            metadata=api.ObjectMeta(name="j1", namespace="default"),
            spec=api.JobSpec(parallelism=2, completions=2,
                             selector={"job": "j1"})), "default")
        client.create("pods", mkpod("j1-a", {"job": "j1"},
                                    phase="Succeeded"), "default")
        code, out, _ = run_cli(client, "delete", "jobs", "j1")
        assert code == 0 and "jobs/j1 deleted" in out
        from kubernetes_tpu.core.errors import NotFound as NF
        with pytest.raises(NF):
            client.get("jobs", "j1", "default")
        assert all(p.metadata.labels.get("job") != "j1"
                   for p in client.list("pods", "default")[0])

    def test_edit_roundtrip(self, cluster, tmp_path, monkeypatch):
        _, client = cluster
        client.create("pods", mkpod("web"), "default")
        # an "editor" that rewrites the image in place
        editor = tmp_path / "ed.sh"
        editor.write_text(
            "#!/bin/sh\nsed -i 's/img/img:edited/' \"$1\"\n")
        editor.chmod(0o755)
        monkeypatch.setenv("EDITOR", str(editor))
        code, out, _ = run_cli(client, "edit", "pod", "web")
        assert code == 0 and "edited" in out
        assert client.get("pods", "web",
                          "default").spec.containers[0].image == "img:edited"

    def test_explain_walks_fields(self, cluster):
        _, client = cluster
        code, out, _ = run_cli(client, "explain", "pods.spec.containers")
        assert code == 0
        assert "KIND:     Pod" in out
        assert "image" in out and "resources" in out

    def test_convert_canonicalizes(self, cluster, tmp_path):
        _, client = cluster
        path = self._manifest(tmp_path, {
            "kind": "Pod", "apiVersion": "v1",
            "metadata": {"name": "x"},
            "spec": {"containers": [{"name": "c", "image": "i"}]}})
        code, out, _ = run_cli(client, "convert", "-f", path)
        assert code == 0
        doc = json.loads(out)
        assert doc["kind"] == "Pod" and doc["metadata"]["name"] == "x"

    def test_namespace_deprecation(self, cluster):
        _, client = cluster
        code, out, _ = run_cli(client, "namespace")
        assert code == 0 and "superseded" in out


class TestProxy:
    def test_proxy_relays_with_credentials(self):
        """kubectl proxy: local plain-HTTP door, credentials attached
        upstream (the reference's cmd/proxy.go contract)."""
        import urllib.request

        from kubernetes_tpu.api.client import HttpClient
        from kubernetes_tpu.api.server import ApiServer
        from kubernetes_tpu.auth.authenticate import BasicAuthAuthenticator
        from kubernetes_tpu.cli.cmd import Kubectl

        registry = Registry()
        InProcClient(registry).create("pods", mkpod("via-proxy"),
                                      "default")
        srv = ApiServer(
            registry,
            authenticator=BasicAuthAuthenticator.from_lines(
                ["pw,admin,1"])).start()
        try:
            import base64
            creds = {"Authorization":
                     "Basic " + base64.b64encode(b"admin:pw").decode()}
            http = HttpClient(srv.url, headers=creds)
            out = io.StringIO()
            k = Kubectl(http, out=out)
            assert k.proxy(port=0, block=False) == 0
            proxy_srv = k._proxy_server
            try:
                # NO credentials on the local hop: the proxy adds them
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{proxy_srv.port}"
                    "/api/v1/namespaces/default/pods",
                    timeout=10).read()
                assert b"via-proxy" in body
            finally:
                proxy_srv.stop()
        finally:
            srv.stop()


class TestConfigCommand:
    """kubectl config over a real kubeconfig file (ref:
    pkg/kubectl/cmd/config; wire shape = clientcmd v1 Config)."""

    def test_build_view_switch_roundtrip(self, tmp_path, monkeypatch):
        path = str(tmp_path / "kubeconfig")
        monkeypatch.setenv("KUBECONFIG", path)

        def cfg(*args):
            out, err = io.StringIO(), io.StringIO()
            code = main(["config", *args], out=out, err=err)
            return code, out.getvalue(), err.getvalue()

        assert cfg("set-cluster", "prod",
                   "--server", "http://10.0.0.1:8080")[0] == 0
        assert cfg("set-credentials", "alice", "--token", "t0k")[0] == 0
        assert cfg("set-context", "prod-ctx", "--cluster", "prod",
                   "--user", "alice", "--context-namespace", "team")[0] == 0
        code, out, err = cfg("current-context")
        assert code == 1 and "not set" in err
        assert cfg("use-context", "prod-ctx")[0] == 0
        code, out, _ = cfg("current-context")
        assert code == 0 and out.strip() == "prod-ctx"
        code, out, _ = cfg("get-contexts")
        assert "*" in out and "prod-ctx" in out

        # the file the commands produced resolves to a working client
        from kubernetes_tpu.api.kubeconfig import load_kubeconfig
        server, headers, ns = load_kubeconfig(path).resolve()
        assert server == "http://10.0.0.1:8080"
        assert headers["Authorization"] == "Bearer t0k"
        assert ns == "team"

    def test_view_redacts_credentials(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KUBECONFIG", str(tmp_path / "kc"))
        out = io.StringIO()
        main(["config", "set-credentials", "a", "--token", "sekret"],
             out=out, err=io.StringIO())
        out = io.StringIO()
        assert main(["config", "view"], out=out, err=io.StringIO()) == 0
        assert "sekret" not in out.getvalue()
        assert "REDACTED" in out.getvalue()
        out = io.StringIO()
        assert main(["config", "view", "--raw"], out=out,
                    err=io.StringIO()) == 0
        assert "sekret" in out.getvalue()

    def test_save_preserves_unmodeled_fields_and_tightens_mode(
            self, tmp_path, monkeypatch):
        """A kubeconfig written by real kubectl carries fields this
        library doesn't model — mutating commands must not destroy
        them, and writing credentials must tighten a loose mode."""
        import os
        import stat
        path = tmp_path / "kc"
        path.write_text(json.dumps({
            "apiVersion": "v1", "kind": "Config",
            "current-context": "old",
            "preferences": {"colors": True},
            "clusters": [{"name": "prod", "cluster": {
                "server": "https://1.2.3.4",
                "certificate-authority-data": "Q0FEQVRB"}}],
            "users": [{"name": "u", "user": {
                "token": "t", "auth-provider": {"name": "oidc"}}}],
            "contexts": [{"name": "old", "context": {
                "cluster": "prod", "user": "u"}}]}))
        path.chmod(0o644)
        monkeypatch.setenv("KUBECONFIG", str(path))
        assert main(["config", "set-context", "new", "--cluster", "prod",
                     "--user", "u"], out=io.StringIO(),
                    err=io.StringIO()) == 0
        data = json.loads(path.read_text()) \
            if path.read_text().lstrip().startswith("{") else None
        if data is None:
            import yaml
            data = yaml.safe_load(path.read_text())
        assert data["preferences"] == {"colors": True}
        cluster = data["clusters"][0]["cluster"]
        assert cluster["certificate-authority-data"] == "Q0FEQVRB"
        assert cluster["server"] == "https://1.2.3.4"
        user = data["users"][0]["user"]
        assert user["auth-provider"] == {"name": "oidc"}
        assert user["token"] == "t"
        assert {c["name"] for c in data["contexts"]} == {"old", "new"}
        assert stat.S_IMODE(os.stat(path).st_mode) == 0o600

    def test_use_unknown_context_fails(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KUBECONFIG", str(tmp_path / "kc"))
        out, err = io.StringIO(), io.StringIO()
        assert main(["config", "use-context", "nope"],
                    out=out, err=err) == 1
        assert "no context" in err.getvalue()


class TestSortBy:
    """--sort-by jsonpath sorting (ref: pkg/kubectl/sorting_printer.go)."""

    def test_sort_by_name_and_numeric_field(self, cluster):
        _, client = cluster
        for name, replicas in (("zeta", 1), ("alpha", 5), ("mid", 3)):
            client.create("replicationcontrollers",
                          api.ReplicationController(
                              metadata=api.ObjectMeta(name=name,
                                                      namespace="default"),
                              spec=api.ReplicationControllerSpec(
                                  replicas=replicas,
                                  selector={"app": name})))
        code, out, _ = run_cli(client, "get", "rc",
                               "--sort-by", "{.metadata.name}",
                               "-o", "name")
        assert code == 0
        assert [l.split("/")[-1] for l in out.strip().splitlines()] == \
            ["alpha", "mid", "zeta"]
        code, out, _ = run_cli(client, "get", "rc",
                               "--sort-by", "{.spec.replicas}",
                               "-o", "name")
        assert code == 0
        assert [l.split("/")[-1] for l in out.strip().splitlines()] == \
            ["zeta", "mid", "alpha"]

    def test_missing_field_sorts_first(self, cluster):
        _, client = cluster
        labeled = mkpod("b-labeled", labels={"rank": "1"})
        client.create("pods", labeled)
        client.create("pods", mkpod("a-unlabeled"))
        code, out, _ = run_cli(client, "get", "pods",
                               "--sort-by", "{.metadata.labels.rank}",
                               "-o", "name")
        assert code == 0
        assert [l.split("/")[-1] for l in out.strip().splitlines()] == \
            ["a-unlabeled", "b-labeled"]


def test_describe_pod_shows_container_state_and_message(cluster):
    _, client = cluster
    pod = mkpod("dead", phase="Failed")
    pod.status.container_statuses = [api.ContainerStatus(
        name="c", ready=False, restart_count=2,
        state=api.ContainerState(
            terminated=api.ContainerStateTerminated(
                exit_code=7, message="fatal: cache corrupt")))]
    client.create("pods", pod)
    code, out, _ = run_cli(client, "describe", "pod", "dead")
    assert code == 0
    assert "Terminated" in out
    assert "Exit Code:\t7" in out
    assert "fatal: cache corrupt" in out
    assert "Restart Count:\t2" in out


def test_get_output_wide(cluster):
    _, client = cluster
    pod = mkpod("w1")
    pod.status.pod_ip = "10.244.9.9"
    client.create("pods", pod)
    code, out, _ = run_cli(client, "get", "pods", "-o", "wide")
    assert code == 0
    head, row = out.strip().splitlines()[:2]
    assert "IP" in head and "NODE" in head
    assert "10.244.9.9" in row and "n1" in row


def test_cluster_scoped_resources_ignore_defaulted_namespace(cluster):
    # `kubectl get nodes` defaults -n default like every command; the
    # cluster-scoped path must not namespace-filter it away
    _, client = cluster
    client.create("nodes", api.Node(
        metadata=api.ObjectMeta(name="n-scope")))
    code, out, _ = run_cli(client, "get", "nodes")
    assert code == 0 and "n-scope" in out
    code, out, _ = run_cli(client, "describe", "node", "n-scope")
    assert code == 0 and "n-scope" in out
