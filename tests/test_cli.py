"""kubectl CLI against the in-proc client (ref: pkg/kubectl/cmd tests use
canned clients; the command surface mirrors cmd.go:134)."""

import io
import json

import pytest

from kubernetes_tpu.api.client import InProcClient
from kubernetes_tpu.api.registry import Registry
from kubernetes_tpu.cli.cmd import main
from kubernetes_tpu.core import types as api
from kubernetes_tpu.core.quantity import parse_quantity


@pytest.fixture()
def cluster():
    registry = Registry()
    client = InProcClient(registry)
    return registry, client


def run_cli(client, *argv):
    out = io.StringIO()
    err = io.StringIO()
    code = main(list(argv), client=client, out=out, err=err)
    return code, out.getvalue(), err.getvalue()


def mkpod(name, labels=None, phase="Running", node="n1"):
    return api.Pod(
        metadata=api.ObjectMeta(name=name, namespace="default",
                                labels=labels or {}),
        spec=api.PodSpec(node_name=node, containers=[api.Container(
            name="c", image="img")]),
        status=api.PodStatus(
            phase=phase,
            container_statuses=[api.ContainerStatus(
                name="c", ready=(phase == "Running"),
                state=api.ContainerState(
                    running=api.ContainerStateRunning()))]))


class TestGet:
    def test_table_output(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("web-1", {"app": "web"}), "default")
        client.create("pods", mkpod("web-2", {"app": "web"},
                                    phase="Pending"), "default")
        code, out, _ = run_cli(client, "get", "pods")
        assert code == 0
        lines = out.splitlines()
        assert lines[0].split() == ["NAME", "READY", "STATUS", "RESTARTS",
                                    "AGE"]
        assert "web-1" in out and "Running" in out
        assert "web-2" in out and "Pending" in out

    def test_aliases_and_selector(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("a", {"app": "x"}), "default")
        client.create("pods", mkpod("b", {"app": "y"}), "default")
        code, out, _ = run_cli(client, "get", "po", "-l", "app=x")
        assert code == 0
        assert "a" in out and "b" not in out

    def test_json_and_jsonpath(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("web"), "default")
        code, out, _ = run_cli(client, "get", "pod/web", "-o", "json")
        data = json.loads(out)
        assert data["metadata"]["name"] == "web"
        code, out, _ = run_cli(client, "get", "pod/web", "-o",
                               "jsonpath={.spec.nodeName}")
        assert out.strip() == "n1"

    def test_output_name(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w"), "default")
        code, out, _ = run_cli(client, "get", "pods", "-o", "name")
        assert out.strip() == "pods/w"

    def test_mixed_kinds_print_stacked_tables(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w"), "default")
        client.create("services", api.Service(
            metadata=api.ObjectMeta(name="svc1", namespace="default"),
            spec=api.ServiceSpec(selector={"app": "web"},
                                 ports=[api.ServicePort(port=80)])),
            "default")
        code, out, err = run_cli(client, "get", "pods,svc")
        assert code == 0, err
        assert "STATUS" in out and "CLUSTER_IP" in out
        assert "w" in out and "svc1" in out

    def test_get_missing_is_error(self, cluster):
        _, client = cluster
        code, out, err = run_cli(client, "get", "pod/nope")
        assert code == 1
        assert "Error" in err


class TestCreateApplyDelete:
    def test_create_from_file(self, cluster, tmp_path):
        _, client = cluster
        manifest = tmp_path / "pod.json"
        manifest.write_text(json.dumps({
            "kind": "Pod", "apiVersion": "v1",
            "metadata": {"name": "filed", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "img"}]}}))
        code, out, _ = run_cli(client, "create", "-f", str(manifest))
        assert code == 0 and "pods/filed created" in out
        assert client.get("pods", "filed", "default")

    def test_apply_updates(self, cluster, tmp_path):
        _, client = cluster
        doc = {"kind": "ReplicationController", "apiVersion": "v1",
               "metadata": {"name": "rc1", "namespace": "default"},
               "spec": {"replicas": 1, "selector": {"a": "b"},
                        "template": {"metadata": {"labels": {"a": "b"}},
                                     "spec": {"containers": [
                                         {"name": "c", "image": "i"}]}}}}
        manifest = tmp_path / "rc.json"
        manifest.write_text(json.dumps(doc))
        code, out, _ = run_cli(client, "apply", "-f", str(manifest))
        assert "created" in out
        doc["spec"]["replicas"] = 4
        manifest.write_text(json.dumps(doc))
        code, out, _ = run_cli(client, "apply", "-f", str(manifest))
        assert "configured" in out
        assert client.get("replicationcontrollers", "rc1",
                          "default").spec.replicas == 4

    def test_delete_by_selector(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("a", {"app": "x"}), "default")
        client.create("pods", mkpod("b", {"app": "y"}), "default")
        code, out, _ = run_cli(client, "delete", "pods", "-l", "app=x")
        assert code == 0 and "pods/a deleted" in out
        assert len(client.list("pods", "default")[0]) == 1


class TestMutations:
    def rc(self, client, replicas=2):
        return client.create("replicationcontrollers",
                             api.ReplicationController(
                                 metadata=api.ObjectMeta(
                                     name="web", namespace="default"),
                                 spec=api.ReplicationControllerSpec(
                                     replicas=replicas,
                                     selector={"app": "web"},
                                     template=api.PodTemplateSpec(
                                         metadata=api.ObjectMeta(
                                             labels={"app": "web"}),
                                         spec=api.PodSpec(containers=[
                                             api.Container(
                                                 name="c", image="i")])))),
                             "default")

    def test_scale(self, cluster):
        _, client = cluster
        self.rc(client)
        code, out, _ = run_cli(client, "scale", "rc", "web",
                               "--replicas", "5")
        assert code == 0
        assert client.get("replicationcontrollers", "web",
                          "default").spec.replicas == 5

    def test_scale_precondition(self, cluster):
        _, client = cluster
        self.rc(client, replicas=2)
        code, _, err = run_cli(client, "scale", "rc", "web",
                               "--replicas", "5",
                               "--current-replicas", "3")
        assert code == 1 and "precondition" in err

    def test_label_and_annotate(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w"), "default")
        code, _, _ = run_cli(client, "label", "pod", "w", "tier=frontend")
        assert code == 0
        assert client.get("pods", "w",
                          "default").metadata.labels["tier"] == "frontend"
        # no overwrite without the flag
        code, _, err = run_cli(client, "label", "pod", "w", "tier=backend")
        assert code == 1 and "--overwrite" in err
        code, _, _ = run_cli(client, "label", "pod", "w", "tier=backend",
                             "--overwrite")
        assert client.get("pods", "w",
                          "default").metadata.labels["tier"] == "backend"
        # removal via trailing dash
        run_cli(client, "label", "pod", "w", "tier-")
        assert "tier" not in client.get("pods", "w",
                                        "default").metadata.labels
        run_cli(client, "annotate", "pod", "w", "note=hello")
        assert client.get("pods", "w",
                          "default").metadata.annotations["note"] == "hello"
        # removal-only in TYPE/NAME form
        run_cli(client, "label", "pod", "w", "extra=1")
        code, _, err = run_cli(client, "label", "pod/w", "extra-")
        assert code == 0, err
        assert "extra" not in client.get("pods", "w",
                                         "default").metadata.labels

    def test_run_rejects_malformed_labels(self, cluster):
        _, client = cluster
        code, _, err = run_cli(client, "run", "w", "--image", "i",
                               "-l", "foo")
        assert code == 1 and "label" in err
        # no RC with a match-everything selector got created
        assert client.list("replicationcontrollers", "default")[0] == []

    def test_expose_and_autoscale_and_run(self, cluster):
        _, client = cluster
        self.rc(client)
        code, out, _ = run_cli(client, "expose", "rc", "web",
                               "--port", "80")
        assert code == 0
        svc = client.get("services", "web", "default")
        assert svc.spec.selector == {"app": "web"}
        assert svc.spec.cluster_ip.startswith("10.0.0.")

        code, _, _ = run_cli(client, "autoscale", "rc", "web",
                             "--max", "10", "--cpu-percent", "50")
        hpa = client.get("horizontalpodautoscalers", "web", "default")
        assert hpa.spec.max_replicas == 10

        code, _, _ = run_cli(client, "run", "worker", "--image", "img:w",
                             "-r", "3")
        rc = client.get("replicationcontrollers", "worker", "default")
        assert rc.spec.replicas == 3
        assert rc.spec.template.spec.containers[0].image == "img:w"

    def test_rolling_update(self, cluster):
        _, client = cluster
        self.rc(client, replicas=3)
        code, out, _ = run_cli(client, "rolling-update", "web", "web-v2",
                               "--image", "img:v2")
        assert code == 0
        rcs, _ = client.list("replicationcontrollers", "default")
        assert len(rcs) == 1
        assert rcs[0].metadata.name == "web-v2"
        assert rcs[0].spec.replicas == 3
        assert rcs[0].spec.template.spec.containers[0].image == "img:v2"

    def test_rolling_update_with_live_rc_manager(self, cluster):
        # the old RC must not adopt (and then delete) the new RC's pods:
        # the updater disjoints the old selector first
        import time
        from kubernetes_tpu.controllers import ReplicationManager
        _, client = cluster
        self.rc(client, replicas=2)
        mgr = ReplicationManager(client).run()
        try:
            deadline = time.time() + 10
            while time.time() < deadline and len(
                    client.list("pods", "default")[0]) < 2:
                time.sleep(0.05)
            code, out, _ = run_cli(client, "rolling-update", "web",
                                   "web-v2", "--image", "img:v2")
            assert code == 0
            deadline = time.time() + 40  # generous: suite runs under load
            def settled():
                pods = client.list("pods", "default")[0]
                return (len(pods) == 2 and all(
                    p.metadata.labels.get("deployment") == "web-v2"
                    for p in pods))
            while time.time() < deadline and not settled():
                time.sleep(0.1)
            assert settled(), [
                (p.metadata.name, p.metadata.labels)
                for p in client.list("pods", "default")[0]]
        finally:
            mgr.stop()


class TestDescribeAndMisc:
    def test_describe_pod(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w", {"app": "web"}), "default")
        code, out, _ = run_cli(client, "describe", "pod", "w")
        assert code == 0
        assert "Name:\tw" in out and "Image:\timg" in out

    def test_version_and_api_versions(self, cluster):
        _, client = cluster
        code, out, _ = run_cli(client, "version")
        assert "Client Version" in out
        code, out, _ = run_cli(client, "api-versions")
        assert "v1" in out and "extensions/v1beta1" in out

    def test_logs_hollow(self, cluster):
        _, client = cluster
        client.create("pods", mkpod("w"), "default")
        code, out, _ = run_cli(client, "logs", "w")
        assert code == 0 and "state=running" in out
