"""Container lifecycle hook runner.

The reference runs PostStart right after a container starts (a failure
kills the container and fails the start, dockertools/manager.go:1474-
1481) and PreStop before an intentional kill (manager.go:1360); the
handlers are the probe union minus TCP (ref:
pkg/kubelet/lifecycle/handlers.go:49 HandlerRunner.Run — exec runs in
the container, httpGet hits the pod, anything else is an invalid
handler).

One sharpening over the reference: a nonzero exec exit fails the hook
(v1.1's docker exec path surfaced only transport errors, silently
ignoring exit codes — a well-known reference wart).
"""

from __future__ import annotations

import urllib.request

from ..core import types as api


class HookError(Exception):
    pass


class HandlerRunner:
    """(handlers.go:34 NewHandlerRunner; the runtime plays the
    command-runner, the pod IP comes from the kubelet)"""

    def __init__(self, runtime, timeout: float = 30.0):
        self.runtime = runtime
        self.timeout = timeout

    def run(self, pod: api.Pod, container: api.Container,
            handler: api.Handler, pod_ip: str = "") -> None:
        """Raises HookError when the hook fails."""
        if handler.exec is not None:
            try:
                code, output = self.runtime.exec_in_container(
                    pod.metadata.uid, container.name,
                    list(handler.exec.command))
            except Exception as e:
                raise HookError(f"exec hook: {e}") from e
            if code != 0:
                raise HookError(
                    f"exec hook exited {code}: {output[-300:]}")
            return
        if handler.http_get is not None:
            g = handler.http_get
            # pod_ip is the caller's AUTHORITATIVE address (the kubelet
            # filters out the shared placeholder); no fallback to the
            # possibly-placeholder status field
            host = g.host or pod_ip
            if not host:
                raise HookError("httpGet hook: pod has no IP yet")
            port = self._resolve_port(g.port, container)
            url = (f"{(g.scheme or 'HTTP').lower()}://{host}:{port}"
                   f"{g.path or '/'}")
            try:
                # any completed response is success; only a failed
                # request fails the hook (handlers.go runHTTPHandler)
                urllib.request.urlopen(url, timeout=self.timeout).close()
            except urllib.error.HTTPError:
                return  # a status-coded reply IS a completed request
            except Exception as e:
                raise HookError(f"httpGet hook {url}: {e}") from e
            return
        raise HookError(f"invalid handler: {handler}")

    @staticmethod
    def _resolve_port(ref, container: api.Container) -> int:
        """int | numeric string | named container port
        (handlers.go:69 resolvePort; empty defaults to 80)."""
        if ref in (None, ""):
            return 80
        if isinstance(ref, int):
            return ref
        s = str(ref)
        if s.isdigit():
            return int(s)
        for p in container.ports:
            if p.name == s:
                return p.container_port
        raise HookError(f"couldn't find port {s!r} in container "
                        f"{container.name!r}")
