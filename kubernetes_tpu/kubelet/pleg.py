"""PLEG — Pod Lifecycle Event Generator.

Reference: pkg/kubelet/pleg/generic.go — relist() polls the runtime every
relist period, diffs per-container states against the previous snapshot,
and pushes PodLifecycleEvents into the channel the sync loop selects on
(Start :78, relist :102).
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from .container import ContainerState, Runtime

logger = logging.getLogger(__name__)

RELIST_PERIOD = 1.0  # generic.go relistPeriod (1s in the reference too)

CONTAINER_STARTED = "ContainerStarted"
CONTAINER_DIED = "ContainerDied"
CONTAINER_REMOVED = "ContainerRemoved"


@dataclass
class PodLifecycleEvent:
    pod_uid: str
    type: str
    container_name: str


class GenericPLEG:
    def __init__(self, runtime: Runtime,
                 relist_period: float = RELIST_PERIOD):
        self.runtime = runtime
        self.relist_period = relist_period
        self.events: "queue.Queue[PodLifecycleEvent]" = queue.Queue()
        # (pod_uid, container_name) -> (container_id, state)
        self._last: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def relist(self) -> int:
        """One diff pass; returns the number of events emitted."""
        current: Dict[Tuple[str, str], Tuple[str, str]] = {}
        for rp in self.runtime.get_pods():
            for c in rp.containers:
                current[(rp.uid, c.name)] = (c.id, c.state)
        emitted = 0
        for key, (cid, state) in current.items():
            old = self._last.get(key)
            if old is None:
                if state == ContainerState.RUNNING:
                    self._emit(key, CONTAINER_STARTED)
                    emitted += 1
                else:
                    self._emit(key, CONTAINER_DIED)
                    emitted += 1
            elif old[1] != state or old[0] != cid:
                if state == ContainerState.RUNNING:
                    self._emit(key, CONTAINER_STARTED)
                else:
                    self._emit(key, CONTAINER_DIED)
                emitted += 1
        for key in self._last:
            if key not in current:
                self._emit(key, CONTAINER_REMOVED)
                emitted += 1
        self._last = current
        return emitted

    def _emit(self, key: Tuple[str, str], etype: str) -> None:
        self.events.put(PodLifecycleEvent(pod_uid=key[0], type=etype,
                                          container_name=key[1]))

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.relist()
            except Exception:
                # a transient runtime error (daemon restart, CLI
                # hiccup) must not kill the only event source for the
                # kubelet's life — the reference's relist runs under
                # wait.Until and survives errors
                logger.debug("pleg relist failed; retrying",
                             exc_info=True)
            self._stop.wait(self.relist_period)

    def start(self) -> "GenericPLEG":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pleg")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
