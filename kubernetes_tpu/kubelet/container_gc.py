"""Dead-container garbage collection.

Reference: pkg/kubelet/dockertools/container_gc.go + the policy in
pkg/kubelet/container/container_gc.go — the engine daemon keeps dead
container records (for logs and restart counts) and the kubelet prunes
them: per (pod uid, container name) "evict unit" keep at most
MaxPerPodContainer dead instances (newest win), enforce a global
MaxContainers budget evicting oldest-first, skip anything younger than
MinAge, and remove unidentified dead containers (non-kubelet names)
outright. The subprocess/fake runtimes replace records in place (one
per container name), so GC is only wired for runtimes that accumulate
dead attempts and expose dead_containers()/remove_container() — the
daemon runtime.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Tuple

logger = logging.getLogger(__name__)


@dataclass
class ContainerGCPolicy:
    """(ref: kubecontainer.ContainerGCPolicy; kubelet defaults
    --minimum-container-ttl-duration=1m, --maximum-dead-containers-
    per-container=2, --maximum-dead-containers=100)"""
    min_age_seconds: float = 60.0
    max_per_evict_unit: int = 2
    max_dead_containers: int = 100


class ContainerGC:
    """(ref: dockertools.NewContainerGC + GarbageCollect)"""

    def __init__(self, runtime, policy: ContainerGCPolicy = None):
        self.runtime = runtime
        self.policy = policy or ContainerGCPolicy()

    @staticmethod
    def supports(runtime) -> bool:
        return (hasattr(runtime, "dead_containers")
                and hasattr(runtime, "remove_container"))

    def _remove(self, cid: str) -> None:
        try:
            self.runtime.remove_container(cid)
        except Exception:
            # already gone / daemon hiccup: next sweep retries
            logger.warning("container GC: removing %s failed", cid,
                           exc_info=True)

    def garbage_collect(self) -> int:
        """One sweep; -> number of containers removed."""
        p = self.policy
        cutoff = time.time() - p.min_age_seconds
        units: Dict[Tuple[str, str], List[dict]] = {}
        unidentified: List[dict] = []
        removed = 0
        for c in self.runtime.dead_containers():
            if c.get("created", 0) > cutoff:
                continue  # too young (ref: newestGCTime check)
            if c.get("uid") and c.get("name"):
                units.setdefault((c["uid"], c["name"]), []).append(c)
            else:
                unidentified.append(c)
        for c in unidentified:
            self._remove(c["id"])
            removed += 1
        # newest first within each unit; keep max_per_evict_unit
        survivors: List[dict] = []
        for unit, containers in units.items():
            containers.sort(key=lambda c: c.get("created", 0),
                            reverse=True)
            for c in containers[p.max_per_evict_unit:]:
                self._remove(c["id"])
                removed += 1
            survivors.extend(containers[:p.max_per_evict_unit])
        # global budget: evict oldest across units
        excess = len(survivors) - p.max_dead_containers
        if excess > 0:
            survivors.sort(key=lambda c: c.get("created", 0))
            for c in survivors[:excess]:
                self._remove(c["id"])
                removed += 1
        return removed
