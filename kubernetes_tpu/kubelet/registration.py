"""Node registration + status heartbeat for the real kubelet process.

The reference kubelet registers its Node object and then synchronizes
NodeStatus on a timer (ref: pkg/kubelet/kubelet.go registerWithApiserver
/ syncNodeStatus, status conditions Ready/OutOfDisk, daemon endpoints,
node info). The kubemark hollow agent (`agents/hollow_node.py`) carries
its own copy of this loop tuned for fleet multiplexing; this one serves
the single real-kubelet process (`hyperkube kubelet`) with injectable
capacity/port providers.
"""

from __future__ import annotations

import random
import threading
from dataclasses import replace
from typing import Callable, Dict, List, Optional

from ..core import types as api
from ..core.errors import NotFound


class NodeRegistration:
    """Register the Node and keep its status fresh; re-register when
    the node object disappears (crash-only, like the heartbeat loop of
    the reference kubelet)."""

    def __init__(self, client, node_name: str,
                 capacity: Callable[[], Dict],
                 allocatable: Optional[Callable[[], Dict]] = None,
                 daemon_port: Callable[[], int] = lambda: 0,
                 host: str = "127.0.0.1",
                 heartbeat_interval: float = 10.0,
                 labels: Optional[Dict[str, str]] = None,
                 kubelet_version: str = "v1.1.0-tpu",
                 runtime_version: str = "proc://1",
                 jitter_rng: Optional[random.Random] = None):
        """jitter_rng: the heartbeat-phase RNG — pass a seeded
        random.Random to make the beat schedule reproducible (the
        deterministic-harness contract); None keeps the process RNG
        (real kubelets should NOT share a phase)."""
        self.client = client
        self._jitter_rng = jitter_rng
        self.node_name = node_name
        self.capacity = capacity
        self.allocatable = allocatable or capacity
        self.daemon_port = daemon_port
        self.host = host
        self.heartbeat_interval = heartbeat_interval
        self.labels = dict(labels or {})
        self.kubelet_version = kubelet_version
        self.runtime_version = runtime_version
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _conditions(self) -> List[api.NodeCondition]:
        ts = api.now_rfc3339()
        return [
            api.NodeCondition(type="Ready", status="True",
                              reason="KubeletReady",
                              last_heartbeat_time=ts),
            api.NodeCondition(type="OutOfDisk", status="False",
                              reason="KubeletHasSufficientDisk",
                              last_heartbeat_time=ts),
        ]

    def _status(self) -> api.NodeStatus:
        # addresses only when a kubelet server actually listens (port
        # nonzero) — a hollow node without its HTTP surface must not
        # advertise a dialable address
        return api.NodeStatus(
            capacity=self.capacity(),
            allocatable=self.allocatable(),
            conditions=self._conditions(),
            addresses=([api.NodeAddress(type="InternalIP",
                                        address=self.host)]
                       if self.daemon_port() else []),
            daemon_endpoints=api.NodeDaemonEndpoints(
                kubelet_endpoint=api.DaemonEndpoint(
                    port=self.daemon_port())),
            node_info=api.NodeSystemInfo(
                kubelet_version=self.kubelet_version,
                container_runtime_version=self.runtime_version))

    def _node_object(self) -> api.Node:
        return api.Node(
            metadata=api.ObjectMeta(name=self.node_name,
                                    labels=self.labels),
            status=self._status())

    def register(self) -> None:
        try:
            self.client.create("nodes", self._node_object())
        except Exception:
            self.heartbeat_once()  # already registered: refresh status

    def heartbeat_once(self) -> bool:
        """One status sync; True when the apiserver accepted it."""
        try:
            node = self.client.get("nodes", self.node_name)
            self.client.update_status(
                "nodes", replace(node, status=self._status()))
            return True
        except NotFound:
            try:
                self.client.create("nodes", self._node_object())
            except Exception:
                pass
            return True  # re-registration is its own success path
        except Exception:
            return False  # apiserver hiccup: caller retries with backoff

    def _loop(self) -> None:
        # full jitter around the period (uniform over [0.5, 1.5) of the
        # nominal interval): a 5k-node fleet whose kubelets all sleep
        # exactly `heartbeat_interval` heartbeats in lockstep waves —
        # every wave invalidates every cached node encoding at once and
        # the controller's grace window sees synchronized staleness.
        rng = self._jitter_rng or random.Random()
        while not self._stop.is_set():
            self._stop.wait(self.heartbeat_interval * rng.uniform(0.5, 1.5))
            if self._stop.is_set():
                return
            # a failed sync retries on a short backoff instead of
            # leaving the heartbeat stale for a whole period (which at
            # long intervals walks straight into the controller's
            # grace window and an Unknown marking)
            backoff = min(0.2, self.heartbeat_interval / 4)
            attempt = 0
            while not self.heartbeat_once():
                attempt += 1
                if attempt >= 5 or self._stop.is_set():
                    break
                self._stop.wait(min(backoff * (2 ** (attempt - 1)),
                                    self.heartbeat_interval))

    def run(self) -> "NodeRegistration":
        self.register()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"node-status-{self.node_name}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
