"""Kubelet pod sources: file manifests, HTTP manifests, apiserver watch
— merged into one update stream.

Reference: pkg/kubelet/config/{config.go PodConfig + podStorage merge,
file.go sourceFile, apiserver.go NewSourceApiserver, http.go sourceURL}.
Each source periodically reports its FULL pod set; the mux diffs per
source against what it previously reported and emits add/update/delete
to the kubelet's handlers — so a manifest file deleted from the
directory tears its static pod down exactly like an apiserver DELETE.

Static pods get deterministic uids/names suffixed with the node name
(ref: file.go applyDefaults — avoids colliding with apiserver pods).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import urllib.request
from typing import Callable, Dict, List, Optional

from ..core import types as api
from ..core.scheme import Scheme, default_scheme


class PodConfig:
    """The merge point (ref: config.go PodConfig, podStorage.Merge)."""

    def __init__(self, on_add: Callable, on_update: Callable,
                 on_delete: Callable):
        self.on_add = on_add
        self.on_update = on_update
        self.on_delete = on_delete
        self._lock = threading.Lock()
        # source name -> {uid: pod}
        self._known: Dict[str, Dict[str, api.Pod]] = {}

    def set_pods(self, source: str, pods: List[api.Pod]) -> None:
        """One source's full current pod set (SET semantics,
        config.go PodUpdate Op=SET)."""
        with self._lock:
            old = self._known.get(source, {})
            new = {p.metadata.uid: p for p in pods}
            self._known[source] = new
        for uid, pod in new.items():
            prev = old.get(uid)
            if prev is None:
                self.on_add(pod)
            elif prev.metadata.resource_version != \
                    pod.metadata.resource_version or prev != pod:
                self.on_update(prev, pod)
        for uid, prev in old.items():
            if uid not in new:
                self.on_delete(prev)


class _PollingSource:
    """Shared poll loop: fetch() -> List[Pod], reported as a SET."""

    name = "polling"

    def __init__(self, config: PodConfig, node_name: str,
                 interval: float = 1.0, scheme: Scheme = default_scheme):
        self.config = config
        self.node_name = node_name
        self.interval = interval
        self.scheme = scheme
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def fetch(self) -> List[api.Pod]:
        raise NotImplementedError

    def poll_once(self) -> None:
        try:
            pods = self.fetch()
        except Exception:
            return  # transient source failure: keep the last good set
        self.config.set_pods(self.name, pods)

    def start(self):
        self.poll_once()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"pod-source-{self.name}")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()

    def _static_defaults(self, pod: api.Pod, origin: str) -> api.Pod:
        """(ref: file.go/http.go applyDefaults: deterministic uid from
        the origin, name suffixed with the node name, default ns, the
        config-source annotation the kubelet keys static-pod handling
        on — kubetypes.ConfigSourceAnnotation)"""
        digest = hashlib.sha1(origin.encode()).hexdigest()[:16]
        annotations = dict(pod.metadata.annotations)
        annotations["kubernetes.io/config.source"] = self.name
        meta = api.fast_replace(
            pod.metadata,
            uid=pod.metadata.uid or digest,
            name=f"{pod.metadata.name}-{self.node_name}",
            namespace=pod.metadata.namespace or "default",
            annotations=annotations)
        spec = api.fast_replace(pod.spec, node_name=self.node_name)
        return api.fast_replace(pod, metadata=meta, spec=spec)


class FileSource(_PollingSource):
    """Static pods from a manifest directory (ref: file.go sourceFile;
    --pod-manifest-path). One JSON manifest per file."""

    name = "file"

    def __init__(self, config: PodConfig, node_name: str, path: str,
                 interval: float = 1.0, scheme: Scheme = default_scheme):
        super().__init__(config, node_name, interval, scheme)
        self.path = path

    def fetch(self) -> List[api.Pod]:
        if not os.path.isdir(self.path):
            return []
        pods = []
        for entry in sorted(os.listdir(self.path)):
            full = os.path.join(self.path, entry)
            if entry.startswith(".") or not os.path.isfile(full):
                continue
            try:
                with open(full) as f:
                    data = json.load(f)
                pod = self.scheme.decode_dict({**data, "kind": "Pod"})
            except Exception:
                continue  # unparseable manifest: skip, keep the rest
            pods.append(self._static_defaults(pod, f"file:{full}"))
        return pods


class HTTPSource(_PollingSource):
    """Static pods from a manifest URL (ref: http.go sourceURL;
    --manifest-url). The body is one Pod or a PodList."""

    name = "http"

    def __init__(self, config: PodConfig, node_name: str, url: str,
                 interval: float = 1.0, scheme: Scheme = default_scheme):
        super().__init__(config, node_name, interval, scheme)
        self.url = url

    def fetch(self) -> List[api.Pod]:
        with urllib.request.urlopen(self.url, timeout=10) as resp:
            data = json.loads(resp.read())
        if data.get("kind") == "PodList":
            items = [{**i, "kind": "Pod"} for i in data.get("items", [])]
        else:
            items = [{**data, "kind": "Pod"}]
        pods = [self.scheme.decode_dict(item) for item in items]
        # origin keys on identity, not list position: a reordered
        # response must not churn uids (delete+add of every pod)
        return [
            self._static_defaults(
                pod, f"http:{self.url}#{pod.metadata.namespace or 'default'}"
                     f"/{pod.metadata.name}")
            for pod in pods]
