"""Docker registry credential resolution — the image-pull keyring.

Reference: pkg/credentialprovider (keyring.go DockerKeyring — registry
URL index with longest-prefix lookup; config.go DockerConfig /
DockerConfigEntry — the .dockercfg JSON shape with either
username/password or a base64 "auth" blob) and the kubelet's
per-pod resolution (kubelet.go getPullSecretsForPod →
credentialprovider.MakeDockerKeyring over kubernetes.io/dockercfg
secrets). The daemon runtime then carries the matched credential to
the engine as the X-Registry-Auth header on /images/create — the
docker remote API's wire shape.
"""

from __future__ import annotations

import base64
import json
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

DEFAULT_REGISTRY = "index.docker.io"
DOCKERCFG_SECRET_TYPE = "kubernetes.io/dockercfg"
DOCKERCFG_KEY = ".dockercfg"


@dataclass(frozen=True)
class DockerCredential:
    username: str = ""
    password: str = ""
    email: str = ""

    def registry_auth_header(self) -> str:
        """The X-Registry-Auth payload (base64 JSON) the docker remote
        API takes on /images/create."""
        return base64.b64encode(json.dumps({
            "username": self.username, "password": self.password,
            "email": self.email}).encode()).decode()


def _entry_credential(entry: dict) -> Optional[DockerCredential]:
    """One .dockercfg entry -> credential (config.go
    DockerConfigEntry: explicit username/password, or an 'auth' blob
    of base64('user:pass'))."""
    user = entry.get("username", "")
    pwd = entry.get("password", "")
    if not (user or pwd) and entry.get("auth"):
        try:
            decoded = base64.b64decode(entry["auth"]).decode()
        except Exception:
            return None
        user, _, pwd = decoded.partition(":")
    if not (user or pwd):
        return None
    return DockerCredential(username=user, password=pwd,
                            email=entry.get("email", ""))


def _normalize_registry(url: str) -> str:
    """Strip scheme + trailing slash: the keyring matches on the host
    [/path] part (keyring.go urlsToMatch)."""
    for scheme in ("https://", "http://"):
        if url.startswith(scheme):
            url = url[len(scheme):]
    return url.rstrip("/")


def parse_dockercfg(cfg: dict) -> Dict[str, DockerCredential]:
    """.dockercfg JSON -> {registry: credential}. Accepts both the
    bare map and the newer {"auths": {...}} wrapper."""
    if "auths" in cfg and isinstance(cfg["auths"], dict):
        cfg = cfg["auths"]
    out: Dict[str, DockerCredential] = {}
    for registry, entry in cfg.items():
        if not isinstance(entry, dict):
            continue
        cred = _entry_credential(entry)
        if cred is not None:
            out[_normalize_registry(registry)] = cred
    return out


def image_registry(image: str) -> str:
    """The registry part of an image reference: 'reg.example.com/a/b'
    -> 'reg.example.com'; bare 'nginx' / 'library/nginx' -> docker
    hub (keyring.go's default-registry behavior)."""
    first = image.split("/", 1)[0]
    if "/" in image and ("." in first or ":" in first
                        or first == "localhost"):
        return first
    return DEFAULT_REGISTRY


class DockerKeyring:
    """Longest-prefix registry credential index (keyring.go
    BasicDockerKeyring: most-specific match wins — 'reg.io/team'
    beats 'reg.io')."""

    def __init__(self):
        self._index: Dict[str, DockerCredential] = {}
        self._lock = threading.Lock()

    def add(self, registry: str, cred: DockerCredential) -> None:
        with self._lock:
            self._index[_normalize_registry(registry)] = cred

    def add_dockercfg(self, cfg: dict) -> None:
        for registry, cred in parse_dockercfg(cfg).items():
            self.add(registry, cred)

    def lookup(self, image: str) -> List[DockerCredential]:
        """Credentials to TRY, most specific first; empty means pull
        anonymously (keyring.go Lookup returns found=false)."""
        target = _normalize_registry(image_registry(image))
        # strip a DIGEST suffix first ('...@sha256:...'), then the TAG
        # — the last ':' of the final path segment; a registry port
        # ('localhost:5000/x') is not a tag, and without the digest
        # strip 'app@sha256:x' would keep 'app@sha256' and miss every
        # path-scoped credential
        ref = image.split("@", 1)[0]
        head, sep, last = ref.rpartition("/")
        repo_path = head + sep + last.split(":", 1)[0]
        with self._lock:
            matches = []
            for registry, cred in self._index.items():
                # exact-registry match, or a path-scoped entry with a
                # REAL path boundary ('reg.io/team' must not serve
                # 'reg.io/teammate/...' — that would hand one tenant's
                # credential to a sibling path)
                if target == registry or repo_path == registry or \
                        repo_path.startswith(registry + "/"):
                    matches.append((len(registry), cred))
        matches.sort(key=lambda t: -t[0])
        return [c for _l, c in matches]


def keyring_from_secrets(secrets) -> DockerKeyring:
    """kubernetes.io/dockercfg secrets -> keyring (the
    MakeDockerKeyring half: data['.dockercfg'] is base64 JSON)."""
    kr = DockerKeyring()
    for secret in secrets:
        if getattr(secret, "type", "") != DOCKERCFG_SECRET_TYPE:
            continue
        raw = (secret.data or {}).get(DOCKERCFG_KEY, "")
        try:
            kr.add_dockercfg(json.loads(base64.b64decode(raw).decode()))
        except Exception:
            continue  # a malformed secret must not block the others
    return kr


def pull_secrets_for_pod(client, pod) -> list:
    """Resolve pod.spec.imagePullSecrets by name in the pod's
    namespace, skipping the missing (kubelet.go getPullSecretsForPod
    logs-and-continues on absent secrets; transient API errors are
    LOGGED, not silently degraded to an anonymous pull)."""
    import logging
    from ..core.errors import NotFound

    out = []
    for ref in getattr(pod.spec, "image_pull_secrets", []) or []:
        try:
            out.append(client.get("secrets", ref.name,
                                  pod.metadata.namespace))
        except NotFound:
            continue
        except Exception:
            logging.getLogger(__name__).warning(
                "resolving imagePullSecret %s/%s failed",
                pod.metadata.namespace, ref.name, exc_info=True)
            continue
    return out


def runtime_puller(runtime, client):
    """The composed image-pull seam for ImageManager: resolve the
    pod's imagePullSecrets into a keyring and hand the pull (with
    credentials) to the runtime — EnsureImageExists' full reference
    flow (image_puller.go + kubelet.go getPullSecretsForPod)."""
    def pull(image: str, pod) -> None:
        keyring = keyring_from_secrets(
            pull_secrets_for_pod(client, pod))
        runtime.pull_image(image, keyring)

    # explicit protocol flag for ImageManager (wrapper-proof, unlike
    # arity inference)
    pull.takes_pod = True
    return pull
