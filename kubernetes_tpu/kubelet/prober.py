"""Probers: liveness/readiness probe executors + worker manager.

Reference: pkg/probe/{exec,http,tcp} (the executors) and
pkg/kubelet/prober/{manager,worker,prober}.go — one worker per
(pod, container, probe-type) running on the probe period, honoring
initialDelay/success/failure thresholds; liveness failure reports back so
the kubelet restarts the container, readiness flips the ready bit the
status manager publishes.
"""

from __future__ import annotations

import socket
import threading
import time
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from ..core import types as api
from .container import ContainerState

SUCCESS = "success"
FAILURE = "failure"
UNKNOWN = "unknown"


class ProbeResult:
    def __init__(self, result: str, message: str = ""):
        self.result = result
        self.message = message

    def __repr__(self):
        return f"ProbeResult({self.result!r})"


class Prober:
    """Executes one probe (ref: prober.go probe() dispatching to
    pkg/probe executors). Exec probes run against a pluggable runner —
    a fake runtime has no shell; tests and the hollow kubelet inject
    outcomes (the reference execs inside the container via docker)."""

    def __init__(self, exec_runner: Optional[Callable] = None):
        # exec_runner(pod, container, command) -> (ok: bool, output: str)
        self.exec_runner = exec_runner

    def probe(self, probe: api.Probe, pod: api.Pod,
              container: api.Container, pod_ip: str) -> ProbeResult:
        if probe.exec is not None:
            if self.exec_runner is None:
                return ProbeResult(UNKNOWN, "no exec runner")
            ok, output = self.exec_runner(pod, container,
                                          probe.exec.command)
            return ProbeResult(SUCCESS if ok else FAILURE, output)
        if probe.http_get is not None:
            return self._http(probe, pod_ip)
        if probe.tcp_socket is not None:
            return self._tcp(probe, pod_ip)
        return ProbeResult(SUCCESS, "no handler -> success")

    def _http(self, probe: api.Probe, pod_ip: str) -> ProbeResult:
        g = probe.http_get
        host = g.host or pod_ip
        url = f"{g.scheme.lower()}://{host}:{g.port}{g.path or '/'}"
        try:
            with urllib.request.urlopen(
                    url, timeout=probe.timeout_seconds) as resp:
                if 200 <= resp.status < 400:
                    return ProbeResult(SUCCESS, f"HTTP {resp.status}")
                return ProbeResult(FAILURE, f"HTTP {resp.status}")
        except Exception as e:
            return ProbeResult(FAILURE, str(e))

    def _tcp(self, probe: api.Probe, pod_ip: str) -> ProbeResult:
        try:
            with socket.create_connection(
                    (pod_ip, int(probe.tcp_socket.port)),
                    timeout=probe.timeout_seconds):
                return ProbeResult(SUCCESS)
        except Exception as e:
            return ProbeResult(FAILURE, str(e))


class _Worker:
    """(ref: prober/worker.go — one goroutine per probe)"""

    def __init__(self, manager: "ProberManager", pod: api.Pod,
                 container: api.Container, probe_type: str,
                 probe: api.Probe):
        self.manager = manager
        self.pod = pod
        self.container = container
        self.probe_type = probe_type
        self.probe = probe
        self._stop = threading.Event()
        self._successes = 0
        self._failures = 0
        self._seen_restarts: Optional[int] = None
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name=f"probe-{probe_type}-"
                                            f"{container.name}")

    def _run(self) -> None:
        if self.probe.initial_delay_seconds:
            if self._stop.wait(self.probe.initial_delay_seconds):
                return
        while not self._stop.is_set():
            self._probe_once()
            if self._stop.wait(max(self.probe.period_seconds, 0.01)):
                return

    def _probe_once(self) -> None:
        rv = self.manager.runtime_view
        if rv is not None:
            rc = rv(self.pod.metadata.uid, self.container.name)
            if rc is None or rc.state != ContainerState.RUNNING:
                return  # nothing running to probe (worker.go doProbe)
            if rc.restart_count != self._seen_restarts:
                # a restarted container gets a clean slate AND a fresh
                # initial delay keyed off ITS start time — the delay is
                # per container incarnation, not per worker lifetime.
                # Readiness also resets to NOT ready: the previous
                # incarnation's pass must not route traffic to a fresh
                # container that has never been probed (worker.go sets
                # the result to Failure on restart); liveness keeps its
                # counters-only reset — a synthetic failure result here
                # would kill the brand-new container
                first = self._seen_restarts is None
                self._seen_restarts = rc.restart_count
                self._successes = self._failures = 0
                if self.probe_type == self.manager.READINESS and not first:
                    self.manager._report(
                        self.pod, self.container, self.probe_type, False,
                        "container restarted; awaiting readiness probe")
            if (self.probe.initial_delay_seconds and rc.started_at
                    and time.time() - rc.started_at
                    < self.probe.initial_delay_seconds):
                return
        # always probe the manager's LATEST view of the pod — the object
        # captured at add time has no pod IP yet (worker.go re-reads the
        # status through the status manager for the same reason)
        pod = self.manager.pod_for(self.pod.metadata.uid) or self.pod
        result = self.manager.prober.probe(
            self.probe, pod, self.container, pod.status.pod_ip)
        if result.result == SUCCESS:
            self._successes += 1
            self._failures = 0
            if self._successes >= self.probe.success_threshold:
                self.manager._report(pod, self.container,
                                     self.probe_type, True, result.message)
        elif result.result == FAILURE:
            self._failures += 1
            self._successes = 0
            if self._failures >= self.probe.failure_threshold:
                # reset so a persistently-failing probe re-breaches (and
                # re-kills) after each further threshold's worth of
                # failures, matching the reference's per-breach kill
                self._failures = 0
                self.manager._report(pod, self.container,
                                     self.probe_type, False, result.message)

    def start(self) -> None:
        self.thread.start()

    def stop(self) -> None:
        self._stop.set()


class ProberManager:
    """(ref: prober/manager.go AddPod/RemovePod + result caches)"""

    LIVENESS = "liveness"
    READINESS = "readiness"

    def __init__(self, prober: Optional[Prober] = None,
                 on_liveness_failure: Optional[Callable] = None,
                 on_readiness_change: Optional[Callable] = None,
                 runtime_view: Optional[Callable] = None):
        self.prober = prober or Prober()
        # runtime_view(pod_uid, container_name) -> RuntimeContainer|None:
        # lets workers key the initial delay off the CURRENT container's
        # start time, reset counters across restarts, and skip
        # non-running containers (worker.go doProbe); probes proceed
        # unconditionally when no view is wired (standalone use)
        self.runtime_view = runtime_view
        # (pod_uid, container, type) -> (ok, message)
        self.results: Dict[Tuple[str, str, str], Tuple[bool, str]] = {}
        self.on_liveness_failure = on_liveness_failure
        # fn(pod) — fired when a readiness verdict flips, so the kubelet
        # republishes status immediately instead of on the periodic sync
        # (the reference's manager feeds readiness into the status
        # manager the same way)
        self.on_readiness_change = on_readiness_change
        self._workers: Dict[Tuple[str, str, str], _Worker] = {}
        self._pods: Dict[str, api.Pod] = {}
        self._lock = threading.Lock()

    def add_pod(self, pod: api.Pod) -> None:
        """Register probes (idempotent) and refresh the pod view —
        called for adds AND updates so probes see fresh status/spec."""
        with self._lock:
            self._pods[pod.metadata.uid] = pod
        for c in pod.spec.containers:
            for ptype, probe in ((self.LIVENESS, c.liveness_probe),
                                 (self.READINESS, c.readiness_probe)):
                if probe is None:
                    continue
                key = (pod.metadata.uid, c.name, ptype)
                with self._lock:
                    if key in self._workers:
                        continue
                    worker = _Worker(self, pod, c, ptype, probe)
                    self._workers[key] = worker
                worker.start()

    def pod_for(self, pod_uid: str) -> Optional[api.Pod]:
        with self._lock:
            return self._pods.get(pod_uid)

    def remove_pod(self, pod_uid: str) -> None:
        with self._lock:
            self._pods.pop(pod_uid, None)
            for key in [k for k in self._workers if k[0] == pod_uid]:
                self._workers.pop(key).stop()
            for key in [k for k in self.results if k[0] == pod_uid]:
                self.results.pop(key, None)

    def _has_readiness_probe(self, pod_uid: str, container: str) -> bool:
        with self._lock:
            return (pod_uid, container, self.READINESS) in self._workers

    def is_ready(self, pod_uid: str, container: str) -> bool:
        """No readiness probe -> ready by default; a probe that hasn't
        reported yet -> NOT ready (the app hasn't proven itself — the
        reference starts containers unready until the first success)."""
        result = self.results.get((pod_uid, container, self.READINESS))
        if result is None:
            return not self._has_readiness_probe(pod_uid, container)
        return result[0]

    def _report(self, pod: api.Pod, container: api.Container,
                probe_type: str, ok: bool, message: str) -> None:
        key = (pod.metadata.uid, container.name, probe_type)
        prev = self.results.get(key)
        self.results[key] = (ok, message)
        changed = prev is None or prev[0] != ok
        if (probe_type == self.LIVENESS and not ok
                and self.on_liveness_failure is not None):
            # every threshold breach kills (the worker resets its counter
            # per breach), not just the first ok->fail transition
            self.on_liveness_failure(pod, container.name, message)
        if (probe_type == self.READINESS and changed
                and self.on_readiness_change is not None):
            self.on_readiness_change(pod)

    def stop(self) -> None:
        with self._lock:
            for worker in self._workers.values():
                worker.stop()
            self._workers.clear()
