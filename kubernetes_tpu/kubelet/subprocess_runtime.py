"""Subprocess runtime — pods as local process groups.

The real-runtime adapter proving the Runtime boundary isn't fake-shaped:
where the reference's largest node-plane component drives a docker daemon
over HTTP (pkg/kubelet/dockertools/manager.go, 2,090 LoC), this drives
the local OS. Each container is one child process (its `command`/`args`,
environment from `env`), each pod is a process group session, logs are
captured files, exec runs inside the pod's environment, and stats come
from the children's /proc — which also makes this the runtime-side
metering source for /stats/summary (kubelet/stats.py).

The kubelet's sync loop, PLEG relist, restart backoff, probers, and the
KubeletServer endpoints all run against it unchanged.
"""

from __future__ import annotations

import os
import signal
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core import types as api
from .container import (ContainerState, Runtime, RuntimeContainer,
                        RuntimePod, tail_text)

_CLK_TCK = os.sysconf("SC_CLK_TCK")
_PAGE = os.sysconf("SC_PAGE_SIZE")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_PAUSE_SRC = os.path.join(_NATIVE_DIR, "pause.c")
_PAUSE_BIN = os.path.join(_NATIVE_DIR, "pause")


def _build_pause() -> Optional[str]:
    """Compile native/pause.c on first use; -> binary path, or None
    (no toolchain / unwritable dir — callers fall back to sleep)."""
    from ..native.build import build_native
    return build_native(_PAUSE_SRC, _PAUSE_BIN,
                        [["cc", "-O2", "-static"], ["cc", "-O2"]])


class _Proc:
    def __init__(self, popen: subprocess.Popen, record: RuntimeContainer,
                 log_path: str, env: Dict[str, str],
                 term_path: str = ""):
        self.popen = popen
        self.record = record
        self.log_path = log_path
        self.env = env
        self.term_path = term_path


class ExecSession:
    """One interactive exec'd process: live output reads, stdin writes,
    exit code. read() blocks (callers pump it from a thread, exactly as
    the attach output pump does)."""

    def __init__(self, popen: subprocess.Popen):
        self._popen = popen

    def read(self, n: int = 65536) -> bytes:
        """Next piece of merged stdout/stderr; b'' at process EOF."""
        out = self._popen.stdout
        return out.read1(n) if out is not None else b""

    def write_stdin(self, data: bytes) -> None:
        if self._popen.stdin is None:
            raise OSError("exec session has no stdin")
        self._popen.stdin.write(data)
        self._popen.stdin.flush()

    def close_stdin(self) -> None:
        if self._popen.stdin is not None:
            try:
                self._popen.stdin.close()
            except OSError:
                pass

    def running(self) -> bool:
        return self._popen.poll() is None

    def exit_code(self, timeout: float = 30.0) -> int:
        return self._popen.wait(timeout=timeout)

    def kill(self) -> None:
        if self._popen.poll() is None:
            try:
                self._popen.kill()
            except OSError:
                pass
        self.close_stdin()


class SubprocessRuntime(Runtime):
    """(ref: the dockertools/manager.go role, OS-process transport)"""

    def __init__(self, root_dir: Optional[str] = None,
                 default_command: Optional[List[str]] = None,
                 termination_grace: float = 2.0):
        # image-less containers run the default command: the pause
        # container (native/pause.c, the reference's third_party/pause
        # role — exist, hold the pod, exit 0 on SIGTERM), compiled on
        # first use like the native store; `sleep` is the fallback when
        # no C toolchain is present
        self.root_dir = root_dir or tempfile.mkdtemp(prefix="kubelet-run-")
        os.makedirs(self.root_dir, exist_ok=True)
        self.termination_grace = termination_grace
        if default_command is not None:
            self.default_command = list(default_command)
        else:
            pause = _build_pause()
            self.default_command = ([pause] if pause
                                    else ["sleep", "3600"])
        self._procs: Dict[Tuple[str, str], _Proc] = {}  # (uid, name)
        self._pods: Dict[str, api.Pod] = {}
        self._resolv: Dict[str, str] = {}  # uid -> resolv.conf path
        self._resolv_text: Dict[str, str] = {}  # uid -> written content
        self._lock = threading.Lock()

    def set_pod_dns(self, pod_uid: str, nameservers: List[str],
                    searches: List[str]) -> None:
        """Materialize the pod's resolver config (the kubelet's
        --cluster-dns role). A process pod has no network namespace to
        bind /etc/resolv.conf into, so the file lands at
        ``{root}/{uid}-resolv.conf`` and each container gets
        RESOLV_CONF pointing at it — DNS-aware entrypoints consume it
        (res_init-style libc reload is a container concern either way;
        the reference has the same caveat for running containers)."""
        path = os.path.join(self.root_dir, f"{pod_uid}-resolv.conf")
        text = "".join(f"nameserver {ns}\n" for ns in nameservers)
        if searches:
            text += "search " + " ".join(searches) + "\n"
        with self._lock:
            unchanged = (self._resolv.get(pod_uid) == path
                         and self._resolv_text.get(pod_uid) == text)
        if unchanged:
            return  # called every sync tick; skip byte-identical writes
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
        with self._lock:
            self._resolv[pod_uid] = path
            self._resolv_text[pod_uid] = text

    # ------------------------------------------------------- Runtime API

    def get_pods(self) -> List[RuntimePod]:
        with self._lock:
            self._reap_locked()
            by_uid: Dict[str, RuntimePod] = {}
            for (uid, _), proc in self._procs.items():
                pod = self._pods.get(uid)
                rp = by_uid.setdefault(uid, RuntimePod(
                    uid=uid,
                    name=pod.metadata.name if pod else "",
                    namespace=pod.metadata.namespace if pod else ""))
                rp.containers.append(RuntimeContainer(**vars(proc.record)))
            return list(by_uid.values())

    def start_container(self, pod: api.Pod, container: api.Container
                        ) -> RuntimeContainer:
        uid = pod.metadata.uid
        # args apply whether or not command overrides the entrypoint:
        # the default command plays the image-entrypoint role here, so
        # an args-only spec runs default_command + args (dockertools
        # passes Entrypoint/Cmd independently; an args-only container
        # must not silently run the bare pause loop)
        cmd = (list(container.command) or list(self.default_command)) \
            + list(container.args)
        env = {**os.environ,
               **{e.name: e.value for e in container.env}}
        with self._lock:
            resolv = self._resolv.get(uid)
        if resolv is not None and not any(
                e.name == "RESOLV_CONF" for e in container.env):
            # only an explicit container env entry may override — an
            # inherited host RESOLV_CONF must not mask the pod's config
            env["RESOLV_CONF"] = resolv
        # termination-message file (types.go:804 TerminationMessagePath):
        # process pods share one filesystem, so the declared path maps to
        # a per-container file exported as TERMINATION_MESSAGE_PATH —
        # the container writes its dying words there and the kubelet
        # reads them into terminated.message
        term_path = ""
        if container.termination_message_path:
            term_path = os.path.join(
                self.root_dir, f"{uid}-{container.name}-term.msg")
            # an explicit container env entry wins — and the reader
            # must follow the SAME path the container was told
            term_path = env.setdefault("TERMINATION_MESSAGE_PATH",
                                       term_path)
            try:
                # never inherit the previous instance's dying words
                os.unlink(term_path)
            except OSError:
                pass
        log_path = os.path.join(
            self.root_dir, f"{uid}-{container.name}.log")
        with self._lock:
            prior = self._procs.get((uid, container.name))
            restart_count = (prior.record.restart_count + 1
                             if prior is not None else 0)
            if prior is not None and os.path.exists(log_path):
                # a restart rotates the dead instance's log so `kubectl
                # logs --previous` can reach it (the docker runtime
                # keeps the terminated container's log the same way)
                try:
                    os.replace(log_path, log_path + ".prev")
                except OSError:
                    pass
            log = open(log_path, "ab")
            try:
                # each container leads its own session so kill targets the
                # whole process tree (the pod "cgroup"). stdin: a pipe
                # only for stdin:true containers (types.go:813 — that is
                # what `kubectl attach -i` reaches); everything else gets
                # devnull, so stdin-until-EOF commands exit promptly
                # instead of blocking on a never-closed pipe
                popen = subprocess.Popen(
                    cmd,
                    stdin=(subprocess.PIPE if container.stdin
                           else subprocess.DEVNULL),
                    stdout=log, stderr=subprocess.STDOUT, env=env,
                    cwd=self.root_dir, start_new_session=True)
            except OSError as e:
                raise RuntimeError(
                    f"start {container.name}: {e}") from e
            finally:
                log.close()
            record = RuntimeContainer(
                id=f"proc://{popen.pid}", name=container.name,
                image=container.image, state=ContainerState.RUNNING,
                started_at=time.time(), restart_count=restart_count)
            self._procs[(uid, container.name)] = _Proc(
                popen, record, log_path, env, term_path)
            self._pods[uid] = pod
            return RuntimeContainer(**vars(record))

    def kill_container(self, pod_uid: str, name: str) -> None:
        with self._lock:
            proc = self._procs.get((pod_uid, name))
        if proc is None:
            return
        self._kill(proc)

    def kill_pod(self, pod_uid: str,
                 grace_seconds: Optional[float] = None) -> None:
        with self._lock:
            procs = [p for (uid, _), p in self._procs.items()
                     if uid == pod_uid]
        # the grace is a POD-wide bound (dockertools KillPod): TERM
        # every container first, then share one deadline across the
        # waits — serial per-container waits would both multiply the
        # bound and starve later containers of their TERM window
        for proc in procs:
            self._signal_term(proc)
        deadline = time.monotonic() + (grace_seconds
                                       if grace_seconds is not None
                                       else self.termination_grace)
        for proc in procs:
            self._await_or_force(proc, deadline)
        with self._lock:
            for key in [k for k in self._procs if k[0] == pod_uid]:
                del self._procs[key]
            self._pods.pop(pod_uid, None)
            resolv = self._resolv.pop(pod_uid, None)
            self._resolv_text.pop(pod_uid, None)
        if resolv is not None:
            try:
                os.unlink(resolv)
            except OSError:
                pass

    def container_log_path(self, pod_uid: str, name: str) -> str:
        """The captured log file (the follow-stream seam the kubelet
        server tails for ?follow=true)."""
        with self._lock:
            proc = self._procs.get((pod_uid, name))
        if proc is None:
            raise KeyError(f"container {name!r} not found")
        return proc.log_path

    def container_running(self, pod_uid: str, name: str) -> bool:
        with self._lock:
            proc = self._procs.get((pod_uid, name))
        return proc is not None and proc.popen.poll() is None

    def write_stdin(self, pod_uid: str, name: str, data: bytes) -> None:
        """(ref: AttachContainer's stdin stream — dockertools attaches
        to the container's stdin; here it is the child's pipe)"""
        with self._lock:
            proc = self._procs.get((pod_uid, name))
        if proc is None or proc.popen.stdin is None:
            raise KeyError(f"container {name!r} has no stdin")
        proc.popen.stdin.write(data)
        proc.popen.stdin.flush()

    def close_stdin(self, pod_uid: str, name: str) -> None:
        with self._lock:
            proc = self._procs.get((pod_uid, name))
        if proc is not None and proc.popen.stdin is not None:
            try:
                proc.popen.stdin.close()
            except OSError:
                pass

    def get_container_logs(self, pod_uid: str, name: str,
                           tail_lines: int = 0,
                           previous: bool = False) -> str:
        """previous=True reads the last terminated instance's rotated
        log (kubectl logs -p; ref: server.go containerLogs ?previous)."""
        with self._lock:
            proc = self._procs.get((pod_uid, name))
        if proc is None:
            raise KeyError(f"container {name!r} not found")
        path = proc.log_path + (".prev" if previous else "")
        try:
            with open(path, "rb") as f:
                text = f.read().decode(errors="replace")
        except FileNotFoundError:
            if previous:
                raise KeyError(
                    f"no previous instance of container {name!r}")
            text = ""
        return tail_text(text, tail_lines)

    def pod_port_address(self, pod_uid: str, port: int) -> Tuple[str, int]:
        # pods run as host-network process groups: their listeners bind
        # the loopback directly (the pause-container analogue holds no
        # separate netns)
        with self._lock:
            if not any(uid == pod_uid for uid, _ in self._procs):
                raise KeyError(f"pod {pod_uid!r} has no running container")
        return ("127.0.0.1", port)

    def exec_start(self, pod_uid: str, name: str, cmd: List[str],
                   stdin: bool = False) -> "ExecSession":
        """Interactive exec: spawn the command in the container's
        environment with live pipes (ref: pkg/kubelet/server.go:242
        ExecInContainer streaming stdin/stdout over SPDY; the session
        object is our transport-neutral half). stderr merges into
        stdout — one output stream, our documented exec divergence."""
        with self._lock:
            proc = self._procs.get((pod_uid, name))
        if proc is None:
            raise KeyError(f"container {name!r} not found")
        popen = subprocess.Popen(
            cmd, cwd=self.root_dir, env=proc.env,
            stdin=subprocess.PIPE if stdin else subprocess.DEVNULL,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        return ExecSession(popen)

    def exec_in_container(self, pod_uid: str, name: str,
                          cmd: List[str]) -> Tuple[int, str]:
        with self._lock:
            proc = self._procs.get((pod_uid, name))
        if proc is None:
            raise KeyError(f"container {name!r} not found")
        try:
            # the container's environment, as documented — not the
            # kubelet's
            done = subprocess.run(cmd, capture_output=True, text=True,
                                  cwd=self.root_dir, env=proc.env,
                                  timeout=30)
        except subprocess.TimeoutExpired:
            return 124, "exec timed out after 30s\n"
        return done.returncode, done.stdout + done.stderr

    # ----------------------------------------------- stats metering seam

    def container_stats(self, pod_uid: str, name: str) -> dict:
        """CPU/memory for a live container from its /proc entry
        (consumed by kubelet.stats._pod_container_stats)."""
        with self._lock:
            proc = self._procs.get((pod_uid, name))
        if proc is None or proc.popen.poll() is not None:
            return {}
        pid = proc.popen.pid
        try:
            with open(f"/proc/{pid}/stat") as f:
                fields = f.read().rsplit(")", 1)[1].split()
            utime, stime = int(fields[11]), int(fields[12])
            with open(f"/proc/{pid}/statm") as f:
                rss_pages = int(f.read().split()[1])
        except (OSError, IndexError, ValueError):
            return {}
        elapsed = max(time.time() - proc.record.started_at, 1e-3)
        cpu_seconds = (utime + stime) / _CLK_TCK
        return {
            "cpu_usage_nano_cores": int(cpu_seconds / elapsed * 1e9),
            "memory_working_set_bytes": rss_pages * _PAGE,
        }

    # ------------------------------------------------------------ helpers

    def _signal_term(self, proc: _Proc) -> None:
        if proc.popen.poll() is None:
            try:  # the whole session, not just the leader
                os.killpg(proc.popen.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass

    def _await_or_force(self, proc: _Proc, deadline: float) -> None:
        popen = proc.popen
        if popen.poll() is None:
            try:
                popen.wait(timeout=max(0.0,
                                       deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(popen.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                try:
                    popen.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        self._mark_exited(proc)

    def _kill(self, proc: _Proc,
              grace_seconds: Optional[float] = None) -> None:
        """Graceful-then-forced, the docker-stop semantics the kubelet
        relies on (dockertools KillContainer: SIGTERM, grace period,
        SIGKILL): a well-behaved init — the pause program included —
        exits 0 instead of recording rc=-9 on every teardown.
        grace_seconds (the pod's own grace) overrides the default
        TERM->KILL window."""
        self._signal_term(proc)
        self._await_or_force(
            proc, time.monotonic() + (grace_seconds
                                      if grace_seconds is not None
                                      else self.termination_grace))

    def _mark_exited(self, proc: _Proc) -> None:
        rc = proc.popen.poll()
        if rc is None or proc.record.state == ContainerState.EXITED:
            return
        proc.record.state = ContainerState.EXITED
        proc.record.finished_at = time.time()
        # negative returncode = killed by signal; report 128+N like docker
        proc.record.exit_code = rc if rc >= 0 else 128 - rc
        if proc.term_path:
            # the container's dying words (types.go:804; surfaced in
            # terminated.message by the kubelet's status publisher)
            try:
                with open(proc.term_path, "r", errors="replace") as f:
                    # bounded read: the file is untrusted container
                    # output (the reference caps the message too)
                    proc.record.message = f.read(4096).strip()
            except OSError:
                pass

    def _reap_locked(self) -> None:
        for proc in self._procs.values():
            self._mark_exited(proc)
