"""Container manager — node allocatable accounting.

Reference: pkg/kubelet/cm (541 LoC: cgroup setup for node allocatable,
system/kube reserved carve-outs) and NewStubContainerManager
(cmd/kubemark/hollow-node.go:101 — what hollow nodes run). The TPU-native
build has no cgroups to configure; what survives is the accounting
contract: allocatable = capacity - system-reserved - kube-reserved,
published on NodeStatus so the scheduler's resource predicates see the
node's true usable envelope rather than raw capacity.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..core.quantity import Quantity


class ContainerManager:
    """(ref: pkg/kubelet/cm/container_manager.go ContainerManager)"""

    def __init__(self,
                 system_reserved: Optional[Dict[str, Quantity]] = None,
                 kube_reserved: Optional[Dict[str, Quantity]] = None):
        self.system_reserved = dict(system_reserved or {})
        self.kube_reserved = dict(kube_reserved or {})

    def allocatable(self, capacity: Dict[str, Quantity]
                    ) -> Dict[str, Quantity]:
        """capacity minus reservations, floored at zero (a reservation
        larger than capacity must not go negative into the scheduler)."""
        out: Dict[str, Quantity] = {}
        for resource, cap in capacity.items():
            reserved = 0
            for res_map in (self.system_reserved, self.kube_reserved):
                q = res_map.get(resource)
                if q is not None:
                    reserved += q.milli
            out[resource] = Quantity(max(0, cap.milli - reserved))
        return out


def stub_container_manager() -> ContainerManager:
    """(ref: NewStubContainerManager — no reservations; allocatable ==
    capacity, the hollow-node configuration)"""
    return ContainerManager()
