"""Container manager — node allocatable accounting.

Reference: pkg/kubelet/cm (541 LoC: cgroup setup for node allocatable,
system/kube reserved carve-outs) and NewStubContainerManager
(cmd/kubemark/hollow-node.go:101 — what hollow nodes run). The TPU-native
build has no cgroups to configure; what survives is the accounting
contract: allocatable = capacity - system-reserved - kube-reserved,
published on NodeStatus so the scheduler's resource predicates see the
node's true usable envelope rather than raw capacity.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..core.quantity import Quantity


class ContainerManager:
    """(ref: pkg/kubelet/cm/container_manager.go ContainerManager)"""

    def __init__(self,
                 system_reserved: Optional[Dict[str, Quantity]] = None,
                 kube_reserved: Optional[Dict[str, Quantity]] = None):
        self.system_reserved = dict(system_reserved or {})
        self.kube_reserved = dict(kube_reserved or {})

    def allocatable(self, capacity: Dict[str, Quantity]
                    ) -> Dict[str, Quantity]:
        """capacity minus reservations, floored at zero (a reservation
        larger than capacity must not go negative into the scheduler)."""
        out: Dict[str, Quantity] = {}
        for resource, cap in capacity.items():
            reserved = 0
            for res_map in (self.system_reserved, self.kube_reserved):
                q = res_map.get(resource)
                if q is not None:
                    reserved += q.milli
            out[resource] = Quantity(max(0, cap.milli - reserved))
        return out


def stub_container_manager() -> ContainerManager:
    """(ref: NewStubContainerManager — no reservations; allocatable ==
    capacity, the hollow-node configuration)"""
    return ContainerManager()


class ResourceEnforcer:
    """Cgroup-role enforcement for the native (subprocess) runtime.

    The reference's cm sets up cgroups and lets the kernel enforce
    container memory limits (the cgroup OOM killer); process-group
    containers have no cgroup, so this poller plays that role: for
    every container that DECLARES a memory limit it reads the live
    /proc-backed stats through the runtime, records that usage (the
    usage()/node_usage() views cover enforced containers; the summary
    API reads runtime.container_stats directly for everything), and
    kills any container whose working set exceeds its limit — the
    same "OOMKilled"-shaped outcome (exit by kill, restart policy
    decides what happens next). Unlimited containers are skipped
    entirely: no limit, no per-second /proc scan.

    ref: pkg/kubelet/cm/container_manager_linux.go (cgroup setup) +
    dockertools' memory limit plumbing into the container config.
    """

    def __init__(self, runtime, pods_provider,
                 interval: float = 1.0, on_oom=None):
        """pods_provider: () -> List[api.Pod] (the kubelet's bound-pod
        view); on_oom: callback(pod_uid, container_name, usage_bytes,
        limit_bytes) fired after an enforcement kill."""
        import threading
        self.runtime = runtime
        self.pods_provider = pods_provider
        self.interval = interval
        self.on_oom = on_oom
        self._usage: Dict[str, Dict[str, dict]] = {}  # uid -> name -> stats
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.oom_kills = 0

    def start(self) -> "ResourceEnforcer":
        import threading
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="resource-enforcer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def usage(self, pod_uid: str) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v)
                    for k, v in self._usage.get(pod_uid, {}).items()}

    def node_usage(self) -> dict:
        """Aggregate live usage (the node-level summary line)."""
        cpu = mem = 0
        with self._lock:
            for containers in self._usage.values():
                for stats in containers.values():
                    cpu += stats.get("cpu_usage_nano_cores", 0)
                    mem += stats.get("memory_working_set_bytes", 0)
        return {"cpu_usage_nano_cores": cpu,
                "memory_working_set_bytes": mem}

    def sweep_once(self) -> None:
        """One poll+enforce pass (the loop's body; callable from tests
        without timing dependence)."""
        if not hasattr(self.runtime, "container_stats"):
            return
        pods = self.pods_provider() or []
        fresh: Dict[str, Dict[str, dict]] = {}
        for pod in pods:
            uid = pod.metadata.uid
            for container in pod.spec.containers:
                limit = container.resources.limits.get("memory")
                if limit is None:
                    continue  # no limit, no scan
                stats = self.runtime.container_stats(uid, container.name)
                if not stats:
                    continue
                fresh.setdefault(uid, {})[container.name] = stats
                limit_bytes = limit.value
                used = stats.get("memory_working_set_bytes", 0)
                if limit_bytes > 0 and used > limit_bytes:
                    # the cgroup OOM-killer moment
                    try:
                        self.runtime.kill_container(uid, container.name)
                    except Exception:
                        continue
                    self.oom_kills += 1
                    if self.on_oom is not None:
                        try:
                            self.on_oom(uid, container.name, used,
                                        limit_bytes)
                        except Exception:
                            pass
        with self._lock:
            self._usage = fresh

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sweep_once()
            except Exception:
                pass  # crash-only: next tick retries
            self._stop.wait(self.interval)
