"""The kubelet's HTTP server — the node plane's remote surface.

Reference: pkg/kubelet/server.go (InstallDefaultHandlers :210 — /healthz,
/pods, /stats, /spec; InstallDebuggingHandlers :242 — /runningpods,
/containerLogs, /exec, /metrics). Routes:

    GET /healthz
    GET /pods                              PodList the kubelet is running
    GET /runningpods                       the runtime's view
    GET /spec                              machine capacity/allocatable
    GET /stats/summary                     node + per-pod resource stats
    GET /containerLogs/{ns}/{pod}/{container}[?tailLines=N]
    GET /exec/{ns}/{pod}/{container}?command=...&command=...
    GET /metrics

Deliberate divergence: /exec answers with the command's combined output
in a plain HTTP response instead of upgrading to a SPDY stream
(pkg/util/httpstream) — same request surface, simpler transport; the
interactive-stream upgrade is out of the TPU-native scope.
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from ..core import types as api
from ..core.scheme import Scheme, default_scheme
from ..utils.metrics import MetricsRegistry, global_metrics
from .cm import ContainerManager, stub_container_manager
from .stats import FakeStatsProvider, StatsProvider


def kubelet_base_url(node: api.Node) -> str:
    """Resolve a node's kubelet server from its registered daemon
    endpoint + first address (the apiserver relay and in-proc clients
    share this)."""
    port = node.status.daemon_endpoints.kubelet_endpoint.port
    if not port:
        raise KeyError(
            f"node {node.metadata.name!r} has no kubelet endpoint "
            f"registered")
    addr = "127.0.0.1"
    for a in node.status.addresses:
        if a.address:
            addr = a.address
            break
    return f"http://{addr}:{port}"


class KubeletServer:
    """Serves one node's kubelet surface. Decoupled from the kubelet
    implementation through three seams so both the real Kubelet and the
    hollow-node agent can sit behind it: `pod_provider()` -> the bound
    pods, `runtime` (get_pods/logs/exec), `capacity_provider()` -> the
    node's capacity map."""

    def __init__(self, node_name: str,
                 pod_provider: Callable[[], List[api.Pod]],
                 runtime,
                 capacity_provider: Callable[[], dict],
                 stats: Optional[StatsProvider] = None,
                 container_manager: Optional[ContainerManager] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 scheme: Scheme = default_scheme,
                 metrics: Optional[MetricsRegistry] = None,
                 node_log_dir: str = ""):
        self.node_name = node_name
        self.pod_provider = pod_provider
        self.runtime = runtime
        self.capacity_provider = capacity_provider
        self.stats = stats or FakeStatsProvider()
        self.cm = container_manager or stub_container_manager()
        self.scheme = scheme
        self.metrics = metrics or global_metrics
        # /logs/ root (server.go:303 serves /var/log). Opt-in: hollow
        # nodes and tests must not silently serve the real host's logs
        # cluster-wide through the node proxy
        self.node_log_dir = node_log_dir
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                server.handle(self)

            def do_POST(self):
                # the reference registers /run for POST (server.go:247).
                # Drain the body first: unread bytes would be parsed as
                # the NEXT request line on this keep-alive connection
                length = int(self.headers.get("Content-Length") or 0)
                while length > 0:
                    chunk = self.rfile.read(min(length, 65536))
                    if not chunk:
                        break
                    length -= len(chunk)
                server.handle(self)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "KubeletServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # ----------------------------------------------------------- dispatch

    def handle(self, h: BaseHTTPRequestHandler) -> None:
        parsed = urllib.parse.urlsplit(h.path)
        path = parsed.path.rstrip("/")
        query = urllib.parse.parse_qs(parsed.query)
        try:
            if path in ("/healthz", "/healthz/ping"):
                return self._raw(h, 200, b"ok", "text/plain")
            if path == "/metrics":
                return self._raw(h, 200, self.metrics.render().encode(),
                                 "text/plain; version=0.0.4")
            if path == "/pods":
                pods = self.pod_provider()
                return self._json(h, 200,
                                  self.scheme.encode_list("Pod", pods))
            if path == "/runningpods":
                return self._json(h, 200, self._running_pods())
            if path == "/spec":
                capacity = self.capacity_provider()
                return self._json(h, 200, {
                    "nodeName": self.node_name,
                    "capacity": {k: str(v) for k, v in capacity.items()},
                    "allocatable": {
                        k: str(v) for k, v
                        in self.cm.allocatable(capacity).items()}})
            if path in ("/stats", "/stats/summary"):
                summary = self.stats.summary(
                    self.node_name, self.pod_provider(), self.runtime)
                return self._json(h, 200, summary.to_dict())
            if path.startswith("/containerLogs/"):
                return self._container_logs(h, path, query)
            if path.startswith("/exec/"):
                return self._exec(h, path, query)
            if path.startswith("/portForward/"):
                return self._port_forward(h, path, query)
            if path == "/tunnel":
                return self._tunnel(h, query)
            if path.startswith("/attach/"):
                return self._attach(h, path, query)
            if path.startswith("/run/"):
                return self._run(h, path, query)
            if path == "/logs" or path.startswith("/logs/"):
                return self._node_logs(h, path)
            self._raw(h, 404, f"not found: {path}".encode(), "text/plain")
        except KeyError as e:
            self._raw(h, 404, str(e).encode(), "text/plain")
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:
            self._raw(h, 500, repr(e).encode(), "text/plain")

    # ----------------------------------------------------------- handlers

    def _find_pod(self, ns: str, name: str) -> api.Pod:
        for pod in self.pod_provider():
            if pod.metadata.namespace == ns and pod.metadata.name == name:
                return pod
        raise KeyError(f"pod {ns}/{name} not found")

    def _split_target(self, path: str, prefix: str):
        parts = path[len(prefix):].split("/")
        if len(parts) != 3 or not all(parts):
            raise KeyError(f"want {prefix}{{ns}}/{{pod}}/{{container}}")
        return parts  # ns, pod, container

    def _run(self, h, path: str, query: dict) -> None:
        """GET/POST /run/{ns}/{pod}/{container}?cmd=a&cmd=b — run one
        command in a running container, answer its combined output
        (ref: server.go:247 /run -> RunInContainer; the reference also
        accepts cmd as a single space-split param)."""
        ns, pod_name, container = self._split_target(path, "/run/")
        pod = self._find_pod(ns, pod_name)
        cmd = query.get("cmd", [])
        if len(cmd) == 1 and " " in cmd[0]:
            cmd = cmd[0].split()
        if not cmd:
            return self._raw(h, 400, b"missing ?cmd=", "text/plain")
        code, output = self.runtime.exec_in_container(
            pod.metadata.uid, container, cmd)
        self._raw(h, 200 if code == 0 else 500, output.encode(),
                  "text/plain")

    def _node_logs(self, h, path: str) -> None:
        """GET /logs/ — browse the node's log directory (ref:
        server.go:303 /logs/ serving /var/log). Directory listings are
        plain text; files stream as-is. Traversal is clamped to the
        root."""
        if not self.node_log_dir:
            return self._raw(h, 404, b"node log serving disabled",
                             "text/plain")
        rel = path[len("/logs"):].lstrip("/")
        root = os.path.realpath(self.node_log_dir)
        target = os.path.realpath(os.path.join(root, rel))
        if not (target == root or target.startswith(root + os.sep)):
            return self._raw(h, 403, b"forbidden", "text/plain")
        if os.path.isdir(target):
            entries = sorted(os.listdir(target))
            body = "".join(
                e + ("/" if os.path.isdir(os.path.join(target, e))
                     else "") + "\n" for e in entries)
            return self._raw(h, 200, body.encode(), "text/plain")
        try:
            size = os.path.getsize(target)
            f = open(target, "rb")
        except OSError:
            return self._raw(h, 404, b"no such log", "text/plain")
        with f:
            # stream in chunks: node logs can be gigabytes and one
            # slurped bytes object per request would balloon RSS.
            # Copy EXACTLY size bytes — a concurrently growing file
            # must not overrun the declared Content-Length and desync
            # the keep-alive connection — and a mid-stream read error
            # can only drop the connection, never write a second
            # response into the body
            h.send_response(200)
            h.send_header("Content-Type", "text/plain")
            h.send_header("Content-Length", str(size))
            h.end_headers()
            remaining = size
            try:
                while remaining > 0:
                    chunk = f.read(min(remaining, 65536))
                    if not chunk:
                        break
                    h.wfile.write(chunk)
                    remaining -= len(chunk)
            except OSError:
                pass
            if remaining:
                h.close_connection = True  # short body: can't reuse

    def _container_logs(self, h, path: str, query: dict) -> None:
        ns, pod_name, container = self._split_target(path, "/containerLogs/")
        pod = self._find_pod(ns, pod_name)
        tail = int(query.get("tailLines", ["0"])[0])
        follow = query.get("follow", ["false"])[0] in ("true", "1")
        previous = query.get("previous", ["false"])[0] in ("true", "1")
        if follow and not previous \
                and hasattr(self.runtime, "container_log_path"):
            return self._follow_logs(h, pod.metadata.uid, container, tail)
        text = self.runtime.get_container_logs(pod.metadata.uid, container,
                                               tail_lines=tail,
                                               previous=previous)
        self._raw(h, 200, text.encode(), "text/plain")

    def _follow_logs(self, h, uid: str, container: str,
                     tail: int) -> None:
        """?follow=true: chunked tail -f of the captured log until the
        container exits (ref: server.go containerLogs + the docker
        follow stream; runtimes expose container_log_path)."""
        import select as _select

        log_path = self.runtime.container_log_path(uid, container)
        h.send_response(200)
        h.send_header("Content-Type", "text/plain")
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()

        def chunk(payload: bytes) -> None:
            h.wfile.write(f"{len(payload):x}\r\n".encode())
            h.wfile.write(payload + b"\r\n")
            h.wfile.flush()

        try:
            with open(log_path, "rb") as f:
                if tail > 0:
                    head = f.read().decode(errors="replace")
                    from .container import tail_text
                    payload = tail_text(head, tail).encode()
                    if payload:  # an empty chunk IS the terminator
                        chunk(payload)
                while True:
                    data = f.read(65536)
                    if data:
                        chunk(data)
                        continue
                    if not self.runtime.container_running(uid, container):
                        # one final read: output written between the
                        # empty read and the exit check must not race
                        # away
                        data = f.read(65536)
                        if data:
                            chunk(data)
                        break
                    # idle wait doubling as disconnect detection: the
                    # follower sends nothing after its GET, so a readable
                    # client socket means EOF/reset — without this, a
                    # quiet long-running container pins this thread (and
                    # the apiserver's relay) long after the follower left
                    readable, _, _ = _select.select([h.connection], [], [],
                                                    0.2)
                    if readable:
                        h.close_connection = True
                        return
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            h.close_connection = True

    def _port_forward(self, h, path: str, query: dict) -> None:
        """GET /portForward/{ns}/{pod}?port=N, websocket upgrade: binary
        frames carry raw TCP bytes to/from the pod's port (ref:
        pkg/kubelet/server.go PortForward — SPDY there, RFC 6455 here;
        see DIVERGENCES.md transport note)."""
        import socket as _socket

        from ..utils import wsstream

        parts = [p for p in path[len("/portForward/"):].split("/") if p]
        if len(parts) != 2:
            raise KeyError("want /portForward/{ns}/{pod}?port=N")
        ns, pod_name = parts
        pod = self._find_pod(ns, pod_name)
        try:
            port = int(query.get("port", ["0"])[0])
        except ValueError:
            port = 0
        if not 0 < port < 65536:
            return self._raw(h, 400, b"?port= required", "text/plain")
        host, target_port = self.runtime.pod_port_address(
            pod.metadata.uid, port)
        try:
            sock = _socket.create_connection((host, target_port),
                                             timeout=10)
        except OSError as e:
            return self._raw(h, 502,
                             f"dial {host}:{target_port}: {e}".encode(),
                             "text/plain")
        # the dial timeout must not linger: an idle-but-healthy session
        # (quiet pod side) would hit recv timeouts and get torn down
        sock.settimeout(None)
        try:
            if not wsstream.server_handshake(h):
                return

            def write(b: bytes) -> None:
                h.wfile.write(b)
                h.wfile.flush()

            # pod_side: EOF from the pod's socket means the response
            # stream is complete -> send CLOSE, ending the session
            wsstream.bridge(h.rfile.read, write, sock, pod_side=True)
        finally:
            sock.close()
            h.close_connection = True

    def _attach(self, h, path: str, query: dict) -> None:
        """GET /attach/{ns}/{pod}/{container}[?stdin=true], websocket:
        the container's NEW output streams out as binary frames (attach
        starts at now — logs replays history, attach does not), and with
        ?stdin=true client binary frames feed the container's stdin
        (ref: pkg/kubelet/server.go AttachContainer; SPDY there, RFC
        6455 here)."""
        import time as _time

        from ..utils import wsstream

        ns, pod_name, container = self._split_target(path, "/attach/")
        pod = self._find_pod(ns, pod_name)
        uid = pod.metadata.uid
        if not hasattr(self.runtime, "container_log_path"):
            return self._raw(h, 501,
                             b"runtime does not support attach",
                             "text/plain")
        log_path = self.runtime.container_log_path(uid, container)
        want_stdin = query.get("stdin", ["false"])[0] in ("true", "1")
        # Open + seek-to-end BEFORE answering 101: the client may send
        # stdin the instant the handshake lands, and if the seek ran
        # after the container echoed it, that output would sit behind
        # the read position forever. Seeking first can only over-include
        # (a few pre-attach bytes), never lose post-attach output.
        log_file = open(log_path, "rb")
        log_file.seek(0, 2)
        if not wsstream.server_handshake(h):
            log_file.close()
            return
        stop = threading.Event()
        wlock = threading.Lock()

        def write(b: bytes) -> None:
            with wlock:  # output pump and the final CLOSE share the pipe
                h.wfile.write(b)
                h.wfile.flush()

        def out_pump():
            try:
                with log_file as f:
                    while not stop.is_set():
                        data = f.read(65536)
                        if data:
                            wsstream.write_frame(write, data,
                                                 wsstream.BINARY)
                            continue
                        if not self.runtime.container_running(uid,
                                                              container):
                            # final drain: output written between the
                            # empty read and the exit check must not
                            # race away (same move _follow_logs makes)
                            data = f.read(65536)
                            if data:
                                wsstream.write_frame(write, data,
                                                     wsstream.BINARY)
                            break
                        _time.sleep(0.1)
            except (ConnectionError, OSError, ValueError):
                pass
            finally:
                try:
                    wsstream.write_frame(write, b"", wsstream.CLOSE)
                except (ConnectionError, OSError, ValueError):
                    pass

        pump = threading.Thread(target=out_pump, daemon=True)
        pump.start()
        try:
            while True:
                opcode, payload = wsstream.read_frame(h.rfile.read)
                if opcode == wsstream.CLOSE:
                    break
                if opcode == wsstream.TEXT and \
                        payload == wsstream.EOF_MARKER:
                    if want_stdin and hasattr(self.runtime, "close_stdin"):
                        self.runtime.close_stdin(uid, container)
                    continue
                if opcode == wsstream.BINARY and payload and want_stdin:
                    try:
                        self.runtime.write_stdin(uid, container, payload)
                    except (KeyError, OSError):
                        break  # container gone / stdin closed
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            stop.set()
            pump.join(timeout=5)
            h.close_connection = True

    def _tunnel(self, h, query: dict) -> None:
        """GET /tunnel?port=N[&host=...], websocket: the node leg of the
        master->node tunneler (ref: pkg/master/tunneler.go — there the
        master SSHs into the node and dials through sshd; here the
        master opens a websocket and this endpoint dials on its
        behalf). Targets are restricted to the node itself (loopback),
        the SSH tunnel's healthz-and-kubelet use in the reference."""
        import socket as _socket

        from ..utils import wsstream

        try:
            port = int(query.get("port", ["0"])[0])
        except ValueError:
            port = 0
        if not 0 < port < 65536:
            return self._raw(h, 400, b"?port= required", "text/plain")
        host = query.get("host", ["127.0.0.1"])[0]
        # node-local only: loopback plus this kubelet's own bind
        # ADDRESS (the master's tunneler dials the node's registered
        # address — a kubelet bound to its InternalIP is not reachable
        # as 127.0.0.1 even from itself). The node NAME is
        # deliberately NOT accepted: it would be resolved through DNS
        # at dial time, and a name that resolves elsewhere would turn
        # this endpoint into an open proxy
        if host not in ("127.0.0.1", "localhost", "::1", self.host):
            return self._raw(h, 403,
                             b"tunnel targets are node-local only",
                             "text/plain")
        try:
            sock = _socket.create_connection((host, port), timeout=10)
        except OSError as e:
            return self._raw(h, 502, f"dial {host}:{port}: {e}".encode(),
                             "text/plain")
        sock.settimeout(None)
        try:
            if not wsstream.server_handshake(h):
                return

            def write(b: bytes) -> None:
                h.wfile.write(b)
                h.wfile.flush()

            wsstream.bridge(h.rfile.read, write, sock, pod_side=True)
        finally:
            sock.close()
            h.close_connection = True

    def _exec(self, h, path: str, query: dict) -> None:
        """GET /exec/{ns}/{pod}/{container}?command=...[&stdin=true].

        Plain GET: one-shot {exitCode, output} (the original exec
        divergence). With a websocket upgrade and a runtime that
        supports exec_start: INTERACTIVE exec (ref: pkg/kubelet/
        server.go:242 ExecInContainer streaming over SPDY; RFC 6455
        here) — output as binary frames, client binary frames to
        stdin, EOF_MARKER half-closes stdin, and at process exit a
        TEXT frame carrying {"exitCode": N} precedes CLOSE so the
        client can propagate the code the way kubectl exec does."""
        ns, pod_name, container = self._split_target(path, "/exec/")
        pod = self._find_pod(ns, pod_name)
        cmd = query.get("command", [])
        if not cmd:
            return self._raw(h, 400, b"missing command", "text/plain")
        wants_ws = ("websocket" in h.headers.get("Upgrade", "").lower()
                    and "upgrade" in h.headers.get("Connection",
                                                   "").lower())
        if wants_ws and not hasattr(self.runtime, "exec_start"):
            # refuse BEFORE running anything: answering a websocket
            # handshake with one-shot JSON would execute the command,
            # then fail the upgrade — a 502 at the relay after real
            # side effects (and a client retry re-runs the command)
            return self._raw(h, 501,
                             b"runtime does not support interactive exec",
                             "text/plain")
        if not wants_ws:
            code, output = self.runtime.exec_in_container(
                pod.metadata.uid, container, cmd)
            return self._json(h, 200, {"exitCode": code, "output": output})
        self._exec_interactive(h, pod, container, cmd, query)

    def _exec_interactive(self, h, pod, container: str, cmd: list,
                          query: dict) -> None:
        from ..utils import wsstream

        want_stdin = query.get("stdin", ["false"])[0] in ("true", "1")
        try:
            session = self.runtime.exec_start(
                pod.metadata.uid, container, cmd, stdin=want_stdin)
        except KeyError as e:
            return self._raw(h, 404, str(e).encode(), "text/plain")
        if not wsstream.server_handshake(h):
            session.kill()
            return
        wlock = threading.Lock()

        def write(b: bytes) -> None:
            with wlock:  # output pump and the exit/CLOSE share the pipe
                h.wfile.write(b)
                h.wfile.flush()

        def out_pump():
            try:
                while True:
                    data = session.read()
                    if not data:
                        break
                    wsstream.write_frame(write, data, wsstream.BINARY)
                try:
                    code = session.exit_code()
                except subprocess.TimeoutExpired:
                    # stdout EOF without exit (fd handed to a child /
                    # closed deliberately): report the indeterminate
                    # state rather than dying frame-less (a missing
                    # exitCode frame decodes as success client-side)
                    session.kill()
                    code = -1
                wsstream.write_frame(
                    write, json.dumps({"exitCode": code}).encode(),
                    wsstream.TEXT)
            except (ConnectionError, OSError, ValueError):
                pass
            finally:
                try:
                    wsstream.write_frame(write, b"", wsstream.CLOSE)
                except (ConnectionError, OSError, ValueError):
                    pass

        pump = threading.Thread(target=out_pump, daemon=True)
        pump.start()
        try:
            while True:
                opcode, payload = wsstream.read_frame(h.rfile.read)
                if opcode == wsstream.CLOSE:
                    break
                if opcode == wsstream.TEXT and \
                        payload == wsstream.EOF_MARKER:
                    session.close_stdin()
                    continue
                if opcode == wsstream.BINARY and payload and want_stdin:
                    try:
                        session.write_stdin(payload)
                    except OSError:
                        break  # process gone / stdin closed
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            session.kill()
            pump.join(timeout=5)
            h.close_connection = True

    def _running_pods(self) -> dict:
        items = []
        for rp in self.runtime.get_pods():
            items.append({
                "metadata": {"name": rp.name, "namespace": rp.namespace,
                             "uid": rp.uid},
                "spec": {"containers": [
                    {"name": c.name, "image": c.image}
                    for c in rp.containers]}})
        return {"kind": "PodList", "apiVersion": "v1", "items": items}

    # ------------------------------------------------------------ helpers

    def _json(self, h, code: int, payload) -> None:
        self._raw(h, code, json.dumps(payload).encode(), "application/json")

    @staticmethod
    def _raw(h, code: int, payload: bytes, ctype: str) -> None:
        h.send_response(code)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(payload)))
        h.end_headers()
        h.wfile.write(payload)
