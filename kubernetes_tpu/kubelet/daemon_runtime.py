"""Engine-daemon runtime: the kubelet driving an external container
daemon over its HTTP API.

Reference: pkg/kubelet/dockertools/manager.go (2,090 LoC) — the kubelet
never runs containers itself; it is a CLIENT of the engine daemon's
remote API (docker-engine v1.x era endpoints: /containers/create,
/containers/{id}/start, /containers/json, /containers/{id}/kill,
/containers/{id}/logs, /containers/{id}/exec). This adapter proves that
client boundary for the Runtime interface: the kubelet's sync loop and
PLEG run unchanged against a daemon on the other side of a socket.

Pod identity rides the reference's container-naming convention
(dockertools/docker.go BuildDockerName/ParseDockerName):
    k8s_<container>_<podname>_<namespace>_<poduid>_<attempt>
so a daemon that knows nothing about pods still round-trips everything
the kubelet needs to reconstruct RuntimePods from a flat container list.
The mock daemon lives in tests (the FakeDockerClient pattern inverted:
instead of faking the client, we fake the SERVER and keep the real
client code under test).
"""

from __future__ import annotations

import http.client
import json
import math
import time
import urllib.parse
from typing import Dict, List, Optional, Tuple

from ..core import types as api
from .container import (ContainerState, Runtime, RuntimeContainer,
                        RuntimePod, tail_text)

NAME_PREFIX = "k8s"  # ref: dockertools/docker.go containerNamePrefix


def build_container_name(pod: api.Pod, container: api.Container,
                         attempt: int) -> str:
    """(ref: BuildDockerName, underscore-joined identity fields)"""
    return "_".join([NAME_PREFIX, container.name, pod.metadata.name,
                     pod.metadata.namespace, pod.metadata.uid,
                     str(attempt)])


def parse_container_name(name: str) -> Optional[dict]:
    """(ref: ParseDockerName) -> {container, pod, namespace, uid,
    attempt} or None for non-kubelet containers (the daemon may run
    others; the kubelet must ignore them, manager.go GetPods)."""
    name = name.lstrip("/")
    parts = name.split("_")
    if len(parts) != 6 or parts[0] != NAME_PREFIX:
        return None
    try:
        attempt = int(parts[5])
    except ValueError:
        return None
    return {"container": parts[1], "pod": parts[2], "namespace": parts[3],
            "uid": parts[4], "attempt": attempt}


class DaemonError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"daemon HTTP {status}: {message}")
        self.status = status


class DaemonRuntime(Runtime):
    """Runtime implemented as an HTTP client of an engine daemon."""

    def __init__(self, base_url: str, timeout: float = 30.0):
        split = urllib.parse.urlsplit(base_url)
        self.host = split.hostname or "127.0.0.1"
        self.port = split.port or 80
        self.timeout = timeout

    # ------------------------------------------------------------ wire

    def _do(self, method: str, path: str, body: Optional[dict] = None,
            raw: bool = False, headers: Optional[dict] = None,
            timeout: Optional[float] = None):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout or self.timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            hdrs = {"Content-Type": "application/json"} if payload else {}
            hdrs.update(headers or {})
            conn.request(method, path, body=payload, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                raise DaemonError(resp.status,
                                  data.decode(errors="replace")[:500])
            if raw:
                return data
            return json.loads(data) if data else None
        finally:
            conn.close()

    # -------------------------------------------------------- Runtime

    def _list_containers(self, all: bool = True) -> List[dict]:
        return self._do("GET", f"/containers/json?all={int(all)}") or []

    def get_pods(self) -> List[RuntimePod]:
        """Reconstruct pods from the daemon's flat container list
        (ref: manager.go GetPods: list + ParseDockerName + group)."""
        pods: Dict[str, RuntimePod] = {}
        for c in self._list_containers():
            parsed = parse_container_name((c.get("Names") or [""])[0])
            if parsed is None:
                continue  # not ours
            rp = pods.setdefault(parsed["uid"], RuntimePod(
                uid=parsed["uid"], name=parsed["pod"],
                namespace=parsed["namespace"]))
            state = c.get("State", "")
            rp.containers.append(RuntimeContainer(
                id=c["Id"], name=parsed["container"],
                image=c.get("Image", ""),
                state=(ContainerState.RUNNING if state == "running"
                       else ContainerState.EXITED),
                started_at=c.get("StartedAt", 0.0),
                finished_at=c.get("FinishedAt", 0.0),
                exit_code=c.get("ExitCode", 0),
                restart_count=parsed["attempt"]))
        # one record per container name: the LATEST attempt (the daemon
        # keeps dead attempts for logs; the sync loop reasons about the
        # newest, manager.go GetPods keeps them all but SyncPod reads
        # the latest — our Runtime contract is the reduced form)
        for rp in pods.values():
            latest: Dict[str, RuntimeContainer] = {}
            for c in rp.containers:
                cur = latest.get(c.name)
                if cur is None or c.restart_count > cur.restart_count:
                    latest[c.name] = c
            rp.containers = list(latest.values())
        return list(pods.values())

    def _find(self, pod_uid: str, name: Optional[str] = None,
              running_only: bool = False) -> List[dict]:
        out = []
        for c in self._list_containers():
            parsed = parse_container_name((c.get("Names") or [""])[0])
            if parsed is None or parsed["uid"] != pod_uid:
                continue
            if name is not None and parsed["container"] != name:
                continue
            if running_only and c.get("State") != "running":
                continue
            c["_parsed"] = parsed
            out.append(c)
        return out

    def pull_image(self, image: str, keyring=None) -> None:
        """POST /images/create with the registry credential riding the
        X-Registry-Auth header (the docker remote API's auth shape;
        ref: dockertools/docker.go Pull + credentialprovider keyring
        lookup). Credentials are tried most-specific-first; an empty
        keyring pulls anonymously."""
        creds = keyring.lookup(image) if keyring is not None else []
        attempts = creds or [None]
        last = None
        for cred in attempts:
            headers = ({"X-Registry-Auth": cred.registry_auth_header()}
                       if cred is not None else None)
            try:
                self._do(
                    "POST",
                    f"/images/create?fromImage="
                    f"{urllib.parse.quote(image)}",
                    headers=headers)
                return
            except DaemonError as e:
                last = e
        raise last

    def start_container(self, pod: api.Pod, container: api.Container
                        ) -> RuntimeContainer:
        prior = self._find(pod.metadata.uid, container.name)
        attempt = max((c["_parsed"]["attempt"] for c in prior),
                      default=-1) + 1
        cname = build_container_name(pod, container, attempt)
        body = {"Image": container.image,
                "Cmd": list(container.command) + list(container.args),
                "Env": [f"{e.name}={e.value}" for e in container.env],
                "OpenStdin": bool(container.stdin),
                "HostConfig": {}}
        # the runtime half of the security context (pkg/securitycontext
        # provider.go Modify{Container,Host}Config)
        from .securitycontext import (apply_to_container_config,
                                      apply_to_host_config)
        apply_to_container_config(container, body)
        apply_to_host_config(container, body["HostConfig"])
        # pod-level namespace sharing -> engine modes (ref:
        # dockertools/manager.go getPidMode/getIpcMode:1994-2008 and
        # the hostNetwork NetworkMode=host wiring in runContainer)
        if pod.spec.host_network:
            body["HostConfig"]["NetworkMode"] = "host"
        if pod.spec.host_pid:
            body["HostConfig"]["PidMode"] = "host"
        if pod.spec.host_ipc:
            body["HostConfig"]["IpcMode"] = "host"
        created = self._do(
            "POST", f"/containers/create?name={urllib.parse.quote(cname)}",
            body=body)
        cid = created["Id"]
        self._do("POST", f"/containers/{cid}/start")
        return RuntimeContainer(
            id=cid, name=container.name, image=container.image,
            state=ContainerState.RUNNING, restart_count=attempt)

    def kill_container(self, pod_uid: str, name: str) -> None:
        for c in self._find(pod_uid, name, running_only=True):
            self._do("POST", f"/containers/{c['Id']}/kill")

    def kill_pod(self, pod_uid: str,
                 grace_seconds: Optional[float] = None) -> None:
        """Kill every container, then remove the records (ref:
        manager.go KillPod + the GC's container removal). With a grace
        period the engine's graded stop runs (docker-remote
        /containers/{id}/stop?t= — TERM, wait t, KILL) instead of the
        immediate kill."""
        # the grace is a POD-wide bound: each serial stop gets only the
        # REMAINING window (a per-container t would multiply the bound
        # by the container count for TERM-ignoring workloads)
        deadline = (time.monotonic() + grace_seconds
                    if grace_seconds is not None else None)
        for c in self._find(pod_uid):
            if c.get("State") == "running":
                remaining = (max(0, math.ceil(deadline - time.monotonic()))
                             if deadline is not None else None)
                if remaining:
                    # the stop call blocks up to t server-side: give
                    # this one request a timeout of t+slack so a
                    # TERM-ignoring workload can't outlive the client
                    # timeout and kill the teardown thread mid-loop
                    self._do("POST", f"/containers/{c['Id']}/stop"
                                     f"?t={remaining}",
                             timeout=remaining + 15.0)
                else:
                    self._do("POST", f"/containers/{c['Id']}/kill")
            self._do("DELETE", f"/containers/{c['Id']}")

    def get_container_logs(self, pod_uid: str, name: str,
                           tail_lines: int = 0,
                           previous: bool = False) -> str:
        if previous:
            raise KeyError('daemon adapter keeps no previous logs')
        found = self._find(pod_uid, name)
        if not found:
            raise KeyError(f"container {name!r} not found")
        latest = max(found, key=lambda c: c["_parsed"]["attempt"])
        raw = self._do(
            "GET",
            f"/containers/{latest['Id']}/logs?stdout=1&stderr=1",
            raw=True)
        return tail_text(raw.decode(errors="replace"), tail_lines)

    def exec_in_container(self, pod_uid: str, name: str,
                          cmd: List[str]) -> Tuple[int, str]:
        """Exec via the daemon's two-step exec API (create -> start ->
        inspect, ref: dockertools ExecInContainer)."""
        found = self._find(pod_uid, name, running_only=True)
        if not found:
            raise KeyError(f"container {name!r} not running")
        cid = found[0]["Id"]
        ex = self._do("POST", f"/containers/{cid}/exec",
                      body={"Cmd": cmd, "AttachStdout": True,
                            "AttachStderr": True})
        out = self._do("POST", f"/exec/{ex['Id']}/start", body={},
                       raw=True)
        inspect = self._do("GET", f"/exec/{ex['Id']}/json")
        return int(inspect.get("ExitCode", 0)), out.decode(
            errors="replace")

    # ------------------------------------------------ container GC seam

    def dead_containers(self) -> List[dict]:
        """Every exited container the daemon still records, for the
        kubelet's ContainerGC (ref: dockertools/container_gc.go
        evictableContainers): [{id, uid, name, created}] with uid/name
        empty for non-kubelet containers (removed outright by GC)."""
        out = []
        for c in self._list_containers():
            if c.get("State") == "running":
                continue
            parsed = parse_container_name((c.get("Names") or [""])[0])
            out.append({
                "id": c["Id"],
                "uid": parsed["uid"] if parsed else "",
                "name": parsed["container"] if parsed else "",
                "created": c.get("Created", 0)})
        return out

    def remove_container(self, cid: str) -> None:
        self._do("DELETE", f"/containers/{cid}")

    def pod_port_address(self, pod_uid: str, port: int) -> Tuple[str, int]:
        """The daemon reports the container's address (inspect
        NetworkSettings); daemons running host-network answer
        loopback."""
        found = self._find(pod_uid, running_only=True)
        if not found:
            raise KeyError(f"pod {pod_uid!r} has no running container")
        inspect = self._do("GET", f"/containers/{found[0]['Id']}/json")
        addr = (inspect.get("NetworkSettings", {}).get("IPAddress")
                or "127.0.0.1")
        return (addr, port)
