"""The kubelet core: sync loop, pod workers, status publication.

Reference: pkg/kubelet/kubelet.go — Run :897, syncLoop :2277,
syncLoopIteration :2297 (select over apiserver pod updates | PLEG events
| periodic sync | housekeeping), syncPod :1597 (ensure containers match
the spec under the restart policy), HandlePodAdditions/Updates/Deletions
:2394-2452; pod workers pkg/kubelet/pod_workers.go:105,137 (one worker
per pod, latest-update-wins); status manager status/manager.go.

RestartPolicy semantics (syncPod + computePodStatus):
  Always      -> dead containers restart, pod stays Running
  OnFailure   -> restart only on exit code != 0; all succeeded -> pod
                 Succeeded
  Never       -> never restart; any failed -> Failed once none running,
                 all succeeded -> Succeeded
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Dict, List, Optional

from ..agents.hollow_node import StatusManager
from ..api.cache import Informer
from ..core import types as api
from ..core.errors import AlreadyExists
from .container import ContainerState, FakeRuntime, Runtime, RuntimePod
from .lifecycle import HandlerRunner, HookError
from .pleg import GenericPLEG
from .prober import Prober, ProberManager

HOUSEKEEPING_PERIOD = 2.0  # kubelet.go housekeepingPeriod (2s)
SYNC_PERIOD = 10.0
# dead-container GC cadence (ref: kubelet.go StartGarbageCollection,
# container GC on its own 1-minute loop — not every housekeeping tick)
CONTAINER_GC_PERIOD = 60.0
# published when no network plugin supplies a real address (the hollow
# convention); NEVER a valid shaping target — every unplumbed pod
# shares it
PLACEHOLDER_POD_IP = "10.244.0.2"
# static-pod machinery (ref: pkg/kubelet/types annotations +
# pkg/kubelet/mirror_client.go): file/http pods carry config.source;
# their apiserver reflections carry config.mirror and are NEVER run
CONFIG_SOURCE_ANNOTATION = "kubernetes.io/config.source"
CONFIG_MIRROR_ANNOTATION = "kubernetes.io/config.mirror"


def is_static_pod(pod: api.Pod) -> bool:
    return pod.metadata.annotations.get(CONFIG_SOURCE_ANNOTATION) in (
        "file", "http")


def is_mirror_pod(pod: api.Pod) -> bool:
    return CONFIG_MIRROR_ANNOTATION in pod.metadata.annotations


def _parse_resolv_conf(text: str) -> "tuple[List[str], List[str]]":
    """nameserver/search lines of a resolv.conf (kubelet.go:1530
    parseResolvConf; later `search` lines replace earlier ones, the
    resolver's own rule)."""
    nameservers: List[str] = []
    searches: List[str] = []
    for line in text.splitlines():
        fields = line.split("#", 1)[0].split()
        if not fields:
            continue
        if fields[0] == "nameserver" and len(fields) >= 2:
            nameservers.append(fields[1])
        elif fields[0] == "search":
            searches = fields[1:]
    return nameservers, searches


def _rfc3339(epoch: float) -> str:
    """Stable timestamp from the runtime's recorded start time — a fresh
    now() per publish would defeat the status manager's dedup."""
    from datetime import datetime, timezone
    return datetime.fromtimestamp(epoch, timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


logger = logging.getLogger(__name__)

class _PodWorker:
    """One serial worker per pod (pod_workers.go:105 managePodLoop):
    processes the latest requested sync; intermediate requests collapse."""

    def __init__(self, kubelet: "Kubelet", pod_uid: str):
        self.kubelet = kubelet
        self.pod_uid = pod_uid
        self._wake: "queue.Queue[Optional[api.Pod]]" = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"pod-worker-{pod_uid[:8]}")
        self._thread.start()

    def update(self, pod: api.Pod) -> None:
        self._wake.put(pod)

    def stop(self) -> None:
        self._wake.put(None)

    def _loop(self) -> None:
        while True:
            pod = self._wake.get()
            if pod is None:
                return
            # collapse a backlog down to the newest update
            try:
                while True:
                    nxt = self._wake.get_nowait()
                    if nxt is None:
                        return
                    pod = nxt
            except queue.Empty:
                pass
            try:
                self.kubelet.sync_pod(pod)
            except Exception:
                pass  # next update or periodic sync re-drives


def _container_spec_hash(c) -> int:
    """Restart-relevant spec identity for one container (the
    dockertools HashContainer role): image, command/args, ports, env
    names/values, volume mounts, and EFFECTIVE privilege (flat field or
    nested SecurityContext — both surfaces are honored at create, so
    both must trigger the restart). Probes/lifecycle are excluded
    (workers re-read them live)."""
    from .securitycontext import effective_privileged
    return hash((c.image, tuple(c.command), tuple(c.args),
                 tuple((p.name, p.host_port, p.container_port, p.protocol)
                       for p in c.ports),
                 tuple((e.name, e.value) for e in c.env),
                 tuple((m.name, m.mount_path, m.read_only)
                       for m in c.volume_mounts),
                 effective_privileged(c)))


class Kubelet:
    def __init__(self, client, node_name: str,
                 runtime: Optional[Runtime] = None,
                 prober: Optional[Prober] = None,
                 max_restart_backoff: float = 10.0,
                 volume_mgr=None, image_manager=None,
                 manifest_path: Optional[str] = None,
                 manifest_url: Optional[str] = None,
                 master_service_namespace: str = "default",
                 cluster_dns: Optional[str] = None,
                 cluster_domain: str = "",
                 resolver_config: str = "/etc/resolv.conf",
                 recorder=None, network_plugin=None, shaper=None):
        """volume_mgr: a volume.VolumePluginMgr — pod volumes are set up
        before containers start and torn down on deletion (kubelet.go
        syncPod mountExternalVolumes). image_manager: pull-policy
        enforcement before each container start (image_puller.go).
        manifest_path/url: static-pod sources merged with the apiserver
        watch (pkg/kubelet/config)."""
        self.client = client
        self.node_name = node_name
        self.runtime = runtime or FakeRuntime()
        self.volume_mgr = volume_mgr
        self.image_manager = image_manager
        self.manifest_path = manifest_path
        self.manifest_url = manifest_url
        self._sources = []
        self._mounted: set = set()  # pod uids with volumes set up
        self._mirrored: set = set()  # static pod uids with mirrors
        self._tearing_down: set = set()  # uids mid-async-teardown
        self._deadline_failed: set = set()  # uids already failed
        self.pleg = GenericPLEG(self.runtime)
        self.prober_manager = ProberManager(
            prober or Prober(), on_liveness_failure=self._liveness_failed,
            on_readiness_change=self._readiness_changed,
            runtime_view=self._runtime_container)
        self.status_manager = StatusManager(client)
        self._workers: Dict[str, _PodWorker] = {}
        self._pods: Dict[str, api.Pod] = {}  # uid -> latest spec
        self._backoff: Dict[str, float] = {}  # uid/name -> not-before
        # container spec hash at last successful start — the
        # dockertools container-hash role (manager.go computes a spec
        # hash per container and kills/restarts on divergence); a
        # kubelet restart adopts running containers at their current
        # spec rather than restarting the node's workload
        self._container_hash: Dict[str, int] = {}
        self._start_times: Dict[str, str] = {}  # uid -> first-seen time
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._informer: Optional[Informer] = None
        # service watch feeding the env-var projection (kubelet.go:245
        # serviceLister); None until run() — containers started before
        # the first sync just get their declared env, the reference's
        # documented pod-vs-service race (kubelet.go:1400-1403)
        self._service_informer: Optional[Informer] = None
        self.master_service_namespace = master_service_namespace
        # --cluster-dns / --cluster-domain / --resolv-conf
        # (kubelet.go:180,648; getClusterDNS :1465)
        self.cluster_dns = cluster_dns
        self.cluster_domain = cluster_domain
        self.resolver_config = resolver_config
        self._resolv_cache = None  # (mtime, nameservers, searches)
        # container lifecycle events (the reference records Started/
        # Failed/Killing/BackOff through record.EventRecorder;
        # dockertools manager.go + kubelet.go syncPod)
        self.recorder = recorder
        # PostStart/PreStop hook runner (pkg/kubelet/lifecycle)
        self._hooks = HandlerRunner(self.runtime)
        # pod network setup/teardown/status (pkg/kubelet/network;
        # kubelet/network.py). None keeps legacy behavior (no setup,
        # placeholder pod IP).
        self.network_plugin = network_plugin
        if network_plugin is not None:
            # fail fast: a misconfigured plugin must abort kubelet
            # construction (the reference aborts plugin selection on an
            # init error), not yield a node that can never start a pod
            network_plugin.init()
        # uid -> (namespace, name) with network set up; kept on failed
        # teardown so housekeeping retries (like _mounted for volumes)
        self._networked: Dict[str, "tuple[str, str]"] = {}
        self._pod_ips: Dict[str, str] = {}  # uid -> plugin-reported IP
        # pod bandwidth shaping (kubelet.go:652 shaper; bandwidth.py).
        # None + annotated pod -> UndefinedShaper event, like the
        # reference (kubelet.go:1751)
        self.shaper = shaper
        self._shaped: Dict[str, tuple] = {}  # uid -> converged target
        if shaper is not None:
            try:
                shaper.reconcile_interface()
            except Exception:
                logging.exception("shaper interface reconcile")
        self.max_restart_backoff = max_restart_backoff
        from .container_gc import ContainerGC
        self._container_gc = (ContainerGC(self.runtime)
                              if ContainerGC.supports(self.runtime)
                              else None)
        # pod-granular runtimes (cli_runtime) GC their own unit files
        # instead of per-container records (rkt.go:1221 GarbageCollect,
        # driven from the kubelet's GC loop like the container GC)
        self._pod_gc = (self._container_gc is None
                        and hasattr(self.runtime, "garbage_collect"))
        self._last_container_gc = 0.0

    # --------------------------------------------------- pod accounting

    def _worker_for(self, pod: api.Pod) -> _PodWorker:
        uid = pod.metadata.uid
        with self._lock:
            worker = self._workers.get(uid)
            if worker is None:
                worker = _PodWorker(self, uid)
                self._workers[uid] = worker
            return worker

    def get_pods(self) -> List[api.Pod]:
        """Current bound-pod specs (the KubeletServer /pods source;
        ref: kubelet.go GetPods)."""
        with self._lock:
            return list(self._pods.values())

    def handle_pod_addition(self, pod: api.Pod) -> None:
        """(kubelet.go:2394 HandlePodAdditions)"""
        if is_mirror_pod(pod):
            return  # the apiserver reflection of a static pod: never run
        if (pod.metadata.deletion_timestamp is not None
                and not is_static_pod(pod)):
            # a relist (kubelet restart, watch 410 recovery) re-surfaces
            # a mid-termination pod as an ADD: resume the drain instead
            # of restarting its containers (the reference's syncPod
            # checks DeletionTimestamp before running anything)
            self.handle_pod_deletion(pod, confirm_api_delete=True)
            return
        with self._lock:
            self._pods[pod.metadata.uid] = pod
        self.prober_manager.add_pod(pod)
        self._worker_for(pod).update(pod)

    def handle_pod_update(self, old: api.Pod, pod: api.Pod) -> None:
        if is_mirror_pod(pod):
            return
        if (pod.metadata.deletion_timestamp is not None
                and not is_static_pod(pod)):
            # graceful deletion observed: the apiserver marked the pod
            # (registry._pod_graceful_delete) instead of dropping it;
            # the kubelet drains (PreStop hooks + kill) and CONFIRMS
            # with a grace-0 delete once teardown completes (ref:
            # kubelet.go syncLoop deletion handling + the status
            # manager's terminated-pod api delete). ANY update of a
            # marked pod is terminating — not just the None->set
            # transition: a second delete (shorter grace re-stamp) or a
            # PUT/PATCH to a terminating pod used to fall through to
            # the normal path, re-add the pod to _pods, and the worker
            # restarted its containers mid-drain (ADVICE.md medium);
            # handle_pod_deletion dedupes the re-entrant teardown.
            self.handle_pod_deletion(pod, confirm_api_delete=True)
            return
        with self._lock:
            self._pods[pod.metadata.uid] = pod
        # refresh the probers' pod view (pod IP, new probes on spec change)
        self.prober_manager.add_pod(pod)
        self._worker_for(pod).update(pod)

    def handle_pod_deletion(self, pod: api.Pod,
                            confirm_api_delete: bool = False) -> None:
        if is_mirror_pod(pod):
            # deleting the reflection never kills the static pod — but
            # un-note it so the next resync recreates it (out-of-band
            # `kubectl delete` of a mirror heals)
            with self._lock:
                self._mirrored.discard(pod.metadata.annotations.get(
                    CONFIG_MIRROR_ANNOTATION, ""))
            return
        if is_static_pod(pod):
            # drop the apiserver reflection with the source's pod
            # (mirror_client.go DeleteMirrorPod)
            try:
                self.client.delete("pods", pod.metadata.name,
                                   pod.metadata.namespace)
            except Exception:
                pass
            with self._lock:
                self._mirrored.discard(pod.metadata.uid)
        uid = pod.metadata.uid
        with self._lock:
            self._pods.pop(uid, None)
            worker = self._workers.pop(uid, None)
            self._start_times.pop(uid, None)
            self._deadline_failed.discard(uid)
            for key in [k for k in self._backoff
                        if k.startswith(f"{uid}/")]:
                del self._backoff[key]
            for key in [k for k in self._container_hash
                        if k.startswith(f"{uid}/")]:
                del self._container_hash[key]
        if worker:
            worker.stop()
        self.prober_manager.remove_pod(uid)
        self.status_manager.forget(pod)
        # the blocking tail (PreStop hooks can run for seconds) happens
        # off the informer dispatch thread so one slow deletion can't
        # stall every other pod's event processing — the reference
        # scopes kills to per-pod workers the same way. The uid is
        # marked mid-teardown so housekeeping's orphan sweep doesn't
        # kill the containers out from under a running PreStop hook.
        # Re-entrant deletes (every MODIFIED on a marked pod routes
        # here) dedupe on that same marker: a second teardown thread
        # would re-run PreStop hooks against dying containers and its
        # stale-bail could strand the API confirm.
        with self._lock:
            if uid in self._tearing_down:
                return  # a teardown is already draining this pod
            self._tearing_down.add(uid)
        threading.Thread(target=self._tear_down_pod,
                         args=(pod, confirm_api_delete),
                         daemon=True,
                         name=f"pod-teardown-{uid[:8]}").start()

    def _tear_down_pod(self, pod: api.Pod,
                       confirm_api_delete: bool = False) -> None:
        """PreStop hooks → network teardown → kill → volumes, in the
        deletion order the reference keeps; failures stay tracked for
        housekeeping retries."""
        uid = pod.metadata.uid
        completed = False
        try:
            completed = self._tear_down_pod_inner(pod)
        finally:
            with self._lock:
                self._tearing_down.discard(uid)
        if confirm_api_delete and completed:
            # graceful deletion's second half: containers are down, so
            # confirm with a grace-0, uid-guarded delete that actually
            # removes the marked pod from storage (the reference's
            # terminated-pod api delete; the uid precondition keeps a
            # same-name pod recreated during the PreStop drain from
            # being collateral). Transient API errors retry off-thread
            # — a marked pod emits no further watch events to re-drive
            # a dropped confirm.
            from ..api.client import confirm_pod_deletion
            confirm_pod_deletion(self.client, pod)

    def _tear_down_pod_inner(self, pod: api.Pod) -> bool:
        """-> True when the pod was actually torn down; False on the
        stale bail (the caller must then NOT confirm the API delete —
        deleting the object out from under a live re-incarnation)."""
        uid = pod.metadata.uid
        for container in pod.spec.containers:
            try:
                self._run_pre_stop(pod, container.name)
            except Exception:
                logging.exception("pre-stop %s/%s", uid, container.name)
        with self._lock:
            if uid in self._pods:
                # re-added during the hooks (a static pod's manifest
                # restored): this teardown is stale — killing now would
                # destroy the NEW incarnation
                return False
        if self.network_plugin is not None and uid in self._networked:
            # teardown before the pod is killed (exec.go: teardown
            # before the infra container dies); a failed teardown stays
            # tracked so housekeeping retries (like _mounted)
            try:
                self.network_plugin.tear_down_pod(
                    pod.metadata.namespace, pod.metadata.name, uid)
            except Exception:
                logging.exception("network teardown %s", uid)
            else:
                with self._lock:
                    self._networked.pop(uid, None)
                    self._pod_ips.pop(uid, None)
        # the pod's own grace bounds the runtime's TERM->KILL window
        # (dockertools KillContainer receives the DeleteOptions grace;
        # a marked pod carries the server-stamped period, otherwise the
        # spec's)
        grace = (pod.metadata.deletion_grace_period_seconds
                 if pod.metadata.deletion_grace_period_seconds is not None
                 else pod.spec.termination_grace_period_seconds)
        self.runtime.kill_pod(uid, grace_seconds=grace)
        if self.volume_mgr is not None and uid in self._mounted:
            try:
                self.volume_mgr.tear_down_pod_volumes(pod)
            except Exception:
                pass  # stays in _mounted: housekeeping retries it
            else:
                with self._lock:
                    self._mounted.discard(uid)
        return True

    # ----------------------------------------------------------- syncPod

    def sync_pod(self, pod: api.Pod) -> None:
        """(kubelet.go:1597 syncPod, against the runtime's view)"""
        uid = pod.metadata.uid
        if (pod.metadata.deletion_timestamp is not None
                and not is_static_pod(pod)):
            # terminating: the teardown path owns this pod (the
            # reference's syncPod checks DeletionTimestamp before
            # running anything) — a worker update racing the drain must
            # never restart containers a teardown is killing
            return
        if is_static_pod(pod):
            # keep the apiserver reflection alive so the static pod is
            # visible (and carries status) cluster-wide; the periodic
            # resync retries a failed create (mirror_client.go
            # CreateMirrorPod, kubelet.go syncPod mirror leg)
            self._ensure_mirror_pod(pod)
        if self._past_active_deadline(pod):
            # (kubelet.go:1926 pastActiveDeadline -> the pod fails with
            # DeadlineExceeded and its containers die) — once; the
            # resync must not re-record the event every 10s forever
            with self._lock:
                if uid in self._deadline_failed:
                    return
                self._deadline_failed.add(uid)
            if self.recorder:
                self.recorder.eventf(
                    pod, "Normal", "DeadlineExceeded",
                    "Pod was active on the node longer than specified "
                    "deadline")
            # intentional kills run PreStop hooks first, like every
            # other kill path (dockertools/manager.go:1360
            # killContainerInPod runs the hook before the stop)
            for container in pod.spec.containers:
                try:
                    self._run_pre_stop(pod, container.name)
                except Exception:
                    logging.exception("pre-stop %s/%s", uid,
                                      container.name)
            self.runtime.kill_pod(
                uid,
                grace_seconds=pod.spec.termination_grace_period_seconds)
            self.status_manager.set_pod_status(pod, api.PodStatus(
                phase=api.POD_FAILED, reason="DeadlineExceeded",
                message="Pod was active on the node longer than "
                        "specified deadline",
                start_time=pod.status.start_time,
                pod_ip=pod.status.pod_ip))
            return
        runtime_pod = self._runtime_pod(uid)
        by_name = {c.name: c for c in runtime_pod.containers} \
            if runtime_pod else {}
        now = time.time()
        def _gated_setup(kind: str, setup) -> bool:
            """Pod-wide setup step before any container start: failure
            holds the WHOLE pod in backoff (kubelet.go syncPod
            mountExternalVolumes / the infra-container network hook).
            Returns False when the sync must stop here."""
            key = f"{uid}/#{kind}"
            if self._backoff.get(key, 0) > now:
                return False
            try:
                setup()
                self._backoff.pop(key, None)
                self._backoff.pop(f"{key}#d", None)
                return True
            except Exception:
                self._note_backoff(key, now)
                self._publish_status(pod)
                return False

        if self.volume_mgr is not None:
            # EVERY sync — set_up is idempotent and a spec update may
            # declare new volumes
            def _volumes():
                self.volume_mgr.set_up_pod_volumes(pod)
                with self._lock:
                    self._mounted.add(uid)
            if not _gated_setup("volumes", _volumes):
                return
        if hasattr(self.runtime, "set_pod_dns"):
            # materialize the pod's resolver config before any container
            # starts (the dockertools --dns/--dns-search role;
            # idempotent). A failure is a pod-wide setup failure like
            # volumes/network: the sync stops and backs off instead of
            # starting containers with no resolver config (the
            # reference returns the getClusterDNS error from syncPod,
            # kubelet.go:1465)
            def _dns():
                ns, search = self.get_cluster_dns(pod)
                self.runtime.set_pod_dns(uid, ns, search)
            if not _gated_setup("dns", _dns):
                return
        if self.network_plugin is not None and uid not in self._networked:
            # network setup precedes every container (exec.go: setup
            # after infra create, before other containers)
            def _network():
                self.network_plugin.set_up_pod(
                    pod.metadata.namespace, pod.metadata.name, uid)
                with self._lock:
                    self._networked[uid] = (pod.metadata.namespace,
                                            pod.metadata.name)
            if not _gated_setup("network", _network):
                return
        self._reconcile_bandwidth(pod)
        # containers running but no longer in the spec are killed (the
        # reference's SyncPod kills everything not in the desired set,
        # manager.go; PreStop is unknowable here — the old spec is
        # gone — matching the divergence note in _run_pre_stop)
        spec_names = {c.name for c in pod.spec.containers}
        for name, rc in list(by_name.items()):
            if name not in spec_names and \
                    rc.state == ContainerState.RUNNING:
                try:
                    self.runtime.kill_container(uid, name)
                except Exception:
                    pass
                self._container_hash.pop(f"{uid}/{name}", None)
                if self.recorder:
                    self.recorder.eventf(
                        pod, "Normal", "Killing",
                        f"Killing container {name} (removed from spec)")
        for container in pod.spec.containers:
            rc = by_name.get(container.name)
            chash = _container_spec_hash(container)
            key = f"{uid}/{container.name}"  # hash AND backoff key
            if rc is not None and rc.state == ContainerState.RUNNING:
                stored = self._container_hash.get(key)
                if stored is None:
                    # kubelet restart: adopt at current spec
                    self._container_hash[key] = chash
                    continue
                if stored == chash:
                    continue
                # spec changed under a running container: kill (with
                # PreStop, like every intentional kill) and fall
                # through to the restart below (manager.go container
                # hash divergence)
                self._run_pre_stop(pod, container.name)
                try:
                    self.runtime.kill_container(uid, container.name)
                except Exception:
                    continue  # retried next sync
                self._container_hash.pop(key, None)
                if self.recorder:
                    self.recorder.eventf(
                        pod, "Normal", "Killing",
                        f"Killing container {container.name} "
                        f"(spec changed)")
            if rc is not None and rc.state == ContainerState.RUNNING:
                pass  # killed above; restart this sync
            elif rc is not None and not self._should_restart(
                    pod.spec.restart_policy, rc.exit_code):
                continue
            if self._backoff.get(key, 0) > now:
                continue
            try:
                if self.image_manager is not None:
                    # pull policy gates the start (image_puller.go
                    # EnsureImageExists)
                    self.image_manager.ensure_image_exists(pod, container)
                self.runtime.start_container(
                    pod, self._container_with_env(pod, container))
                self._container_hash[key] = chash
                if (container.lifecycle is not None
                        and container.lifecycle.post_start is not None):
                    # a failed PostStart kills the container and fails
                    # the start (manager.go:1474-1481)
                    self._run_post_start(pod, container)
                self._backoff.pop(key, None)
                self._backoff.pop(f"{key}#d", None)  # full delay reset
                if self.recorder:
                    # (dockertools manager.go "Started")
                    self.recorder.eventf(
                        pod, "Normal", "Started",
                        "Started container %s", container.name)
            except Exception as e:
                self._note_backoff(key, now)
                if self.recorder:
                    reason = "BackOff" if rc is not None else "Failed"
                    self.recorder.eventf(
                        pod, "Warning", reason,
                        "Error starting container %s: %s"
                        if reason == "Failed"
                        else "Back-off restarting failed container %s"
                             " (%s)",
                        container.name, e)
        self._publish_status(pod)

    def _ensure_mirror_pod(self, pod: api.Pod) -> None:
        """Create the static pod's apiserver reflection once
        (mirror_client.go:41 CreateMirrorPod: the mirror annotation
        carries the static pod's identity)."""
        with self._lock:
            if pod.metadata.uid in self._mirrored:
                return
        import dataclasses
        annotations = dict(pod.metadata.annotations)
        annotations[CONFIG_MIRROR_ANNOTATION] = pod.metadata.uid
        mirror = dataclasses.replace(
            pod, metadata=dataclasses.replace(
                pod.metadata, uid="", resource_version="",
                annotations=annotations))
        try:
            self.client.create("pods", mirror, pod.metadata.namespace)
        except AlreadyExists:
            pass
        except Exception:
            return  # transient: the periodic resync retries
        with self._lock:
            self._mirrored.add(pod.metadata.uid)

    def _past_active_deadline(self, pod: api.Pod) -> bool:
        """(kubelet.go:1926 pastActiveDeadline)"""
        ads = pod.spec.active_deadline_seconds
        if not ads:
            return False
        start = (pod.status.start_time
                 or self._start_times.get(pod.metadata.uid))
        if not start:
            return False
        from datetime import datetime, timezone
        try:
            started = datetime.strptime(
                start, "%Y-%m-%dT%H:%M:%SZ").replace(
                tzinfo=timezone.utc).timestamp()
        except ValueError:
            return False
        return time.time() - started > ads

    def _hook_ip(self, pod: api.Pod) -> str:
        """The pod IP for httpGet hooks — NEVER the shared placeholder
        (the hook runner fails fast on an empty host and the start is
        retried once a real address exists)."""
        ip = self._pod_ip(pod)
        return "" if ip == PLACEHOLDER_POD_IP else ip

    def _run_post_start(self, pod: api.Pod,
                        container: api.Container) -> None:
        try:
            self._hooks.run(pod, container,
                            container.lifecycle.post_start,
                            pod_ip=self._hook_ip(pod))
        except HookError as e:
            if self.recorder:
                self.recorder.eventf(
                    pod, "Warning", "FailedPostStartHook",
                    "PostStart hook for %s failed: %s",
                    container.name, e)
            self.runtime.kill_container(pod.metadata.uid,
                                        container.name)
            raise  # the start failed: backoff like any start error

    def _run_pre_stop(self, pod: api.Pod,
                      container_name: str) -> None:
        """Best-effort PreStop before an intentional kill
        (manager.go:1360 KillContainerInPod)."""
        spec = next((c for c in pod.spec.containers
                     if c.name == container_name), None)
        if (spec is None or spec.lifecycle is None
                or spec.lifecycle.pre_stop is None):
            return
        rp = self._runtime_pod(pod.metadata.uid)
        running = rp is not None and any(
            c.name == container_name
            and c.state == ContainerState.RUNNING
            for c in rp.containers)
        if not running:
            return
        try:
            self._hooks.run(pod, spec, spec.lifecycle.pre_stop,
                            pod_ip=self._hook_ip(pod))
        except HookError as e:
            if self.recorder:
                self.recorder.eventf(
                    pod, "Warning", "FailedPreStopHook",
                    "PreStop hook for %s failed: %s",
                    container_name, e)

    def _reconcile_bandwidth(self, pod: api.Pod) -> None:
        """Program the pod's bandwidth limits when annotated
        (kubelet.go:1730 syncNetworkStatus bandwidth leg)."""
        from .bandwidth import (EGRESS_ANNOTATION, INGRESS_ANNOTATION,
                                extract_pod_bandwidth)
        ann = pod.metadata.annotations
        if (INGRESS_ANNOTATION not in ann
                and EGRESS_ANNOTATION not in ann):
            return
        try:
            ingress, egress = extract_pod_bandwidth(pod)
        except ValueError as e:
            if self.recorder:
                self.recorder.eventf(
                    pod, "Warning", "InvalidBandwidth", "%s", e)
            return
        if ingress is None and egress is None:
            return
        if pod.spec.host_network or getattr(
                self.network_plugin, "shared_host_address", False):
            # shaping keys on the pod's ip/32; a host-netns pod's
            # address is the NODE's — limiting it would throttle
            # everything on the node (kubelet.go:1735-1736 applies the
            # same refusal to hostNetwork pods)
            if self.recorder:
                self.recorder.eventf(
                    pod, "Warning", "HostNetworkNotSupported",
                    "Bandwidth shaping is not currently supported on "
                    "the host network")
            return
        if self.shaper is None:
            if self.recorder:
                self.recorder.eventf(
                    pod, "Warning", "NilShaper",
                    "Pod requests bandwidth shaping, but the shaper "
                    "is undefined")
            return
        uid = pod.metadata.uid
        with self._lock:
            ip = self._pod_ips.get(uid)
        ip = ip or pod.status.pod_ip
        if not ip or ip == PLACEHOLDER_POD_IP:
            # no REAL per-pod address: shaping the shared placeholder
            # would make annotated pods clobber each other's limits
            return
        desired = (ip,
                   ingress.value if ingress is not None else None,
                   egress.value if egress is not None else None)
        with self._lock:
            if self._shaped.get(uid) == desired:
                return  # converged: skip the tc probes entirely
        try:
            self.shaper.reconcile_cidr(f"{ip}/32", egress, ingress)
        except Exception:
            logging.exception("bandwidth reconcile %s", uid)
        else:
            with self._lock:
                self._shaped[uid] = desired

    def _note_backoff(self, key: str, now: float) -> None:
        prev = self._backoff.get(f"{key}#d", 0.5)
        delay = min(prev * 2, self.max_restart_backoff)
        self._backoff[key] = now + delay
        self._backoff[f"{key}#d"] = delay

    def get_cluster_dns(self, pod: api.Pod
                        ) -> "tuple[List[str], List[str]]":
        """(nameservers, search domains) for a pod (kubelet.go:1465
        getClusterDNS): ClusterFirst pods get only the cluster DNS with
        the {ns}.svc.{domain} / svc.{domain} / {domain} search ladder
        prepended to the host's; other pods (or ClusterFirst with no
        --cluster-dns configured — the MissingClusterDNS fallback) get
        the host resolver's settings."""
        host_dns: List[str] = []
        host_search: List[str] = []
        if self.resolver_config:
            # memoized by mtime: this runs on every pod sync tick
            try:
                mtime = os.stat(self.resolver_config).st_mtime
                cached = self._resolv_cache
                if cached is not None and cached[0] == mtime:
                    host_dns, host_search = cached[1], cached[2]
                else:
                    with open(self.resolver_config) as f:
                        host_dns, host_search = _parse_resolv_conf(
                            f.read())
                    self._resolv_cache = (mtime, host_dns, host_search)
            except OSError:
                # transiently unreadable (non-atomic rewrite by the
                # host's network manager): keep the last good parse
                # rather than materializing a zero-nameserver config.
                # With NO previous parse there is nothing safe to
                # serve — propagate so the pod sync backs off and
                # retries instead of starting the pod with broken DNS
                # (the reference returns the error, kubelet.go:1465)
                if self._resolv_cache is None:
                    raise
                host_dns = self._resolv_cache[1]
                host_search = self._resolv_cache[2]
        cluster_first = (pod.spec.dns_policy or "ClusterFirst") \
            == "ClusterFirst"
        if cluster_first and not self.cluster_dns:
            logging.warning(
                "pod %s wants ClusterFirst DNS but no --cluster-dns is "
                "configured; falling back to host DNS",
                pod.metadata.name)
            cluster_first = False
        if not cluster_first:
            if not self.resolver_config:
                # empty --resolv-conf: the documented "use the local
                # resolver" stance (kubelet.go:1494-1503)
                return ["127.0.0.1"], ["."]
            return host_dns, host_search
        search = ([f"{pod.metadata.namespace}.svc.{self.cluster_domain}",
                   f"svc.{self.cluster_domain}", self.cluster_domain]
                  if self.cluster_domain else []) + host_search
        return [self.cluster_dns], search

    def make_environment(self, pod: api.Pod, container: api.Container
                         ) -> List[api.EnvVar]:
        """The container's final env: declared vars ($(var)-expanded,
        fieldRef-resolved) + service-discovery vars (kubelet.go:1393
        makeEnvironmentVariables; kubelet/envvars.py)."""
        from .envvars import make_environment
        services: List[api.Service] = []
        if self._service_informer is not None:
            services = self._service_informer.cache.list()
        return make_environment(pod, container, services,
                                self.master_service_namespace)

    def _container_with_env(self, pod: api.Pod,
                            container: api.Container) -> api.Container:
        """A copy of the container spec carrying the resolved env, so
        every runtime (subprocess/daemon/cli/fake) starts it with the
        same environment without knowing how it was built. The env is
        deliberately not part of any restart-decision identity — a
        service change must not restart running containers
        (kubelet.go:1395-1398 note)."""
        import dataclasses
        return dataclasses.replace(
            container, env=self.make_environment(pod, container))

    @staticmethod
    def _should_restart(policy: str, exit_code: int) -> bool:
        if policy == "Never":
            return False
        if policy == "OnFailure":
            return exit_code != 0
        return True  # Always

    def _runtime_container(self, uid: str, name: str):
        """Prober view: the CURRENT incarnation of one container (state,
        start time, restart count) — worker.go doProbe's container
        lookup."""
        rp = self._runtime_pod(uid)
        if rp is None:
            return None
        return next((c for c in rp.containers if c.name == name), None)

    def _runtime_pod(self, uid: str) -> Optional[RuntimePod]:
        for rp in self.runtime.get_pods():
            if rp.uid == uid:
                return rp
        return None

    def _readiness_changed(self, pod: api.Pod) -> None:
        current = self._pods.get(pod.metadata.uid)
        if current is not None:
            self._worker_for(current).update(current)

    def _liveness_failed(self, pod: api.Pod, container_name: str,
                         message: str) -> None:
        """Liveness failure -> kill; restart policy decides revival
        (prober feeds syncPod in the reference the same way)."""
        if self.recorder:
            # (kubelet.go "Killing" + prober "Unhealthy")
            self.recorder.eventf(pod, "Warning", "Unhealthy",
                                 "Liveness probe failed: %s", message)
            self.recorder.eventf(pod, "Normal", "Killing",
                                 "Killing container %s", container_name)
        self._run_pre_stop(pod, container_name)
        self.runtime.kill_container(pod.metadata.uid, container_name)
        current = self._pods.get(pod.metadata.uid)
        if current is not None:
            self._worker_for(current).update(current)

    # ----------------------------------------------------------- status

    def _publish_status(self, pod: api.Pod) -> None:
        uid = pod.metadata.uid
        runtime_pod = self._runtime_pod(uid)
        containers = runtime_pod.containers if runtime_pod else []
        by_name = {c.name: c for c in containers}
        statuses: List[api.ContainerStatus] = []
        n_running = n_succeeded = n_failed = 0
        for container in pod.spec.containers:
            rc = by_name.get(container.name)
            if rc is None:
                statuses.append(api.ContainerStatus(
                    name=container.name, image=container.image,
                    state=api.ContainerState(
                        waiting=api.ContainerStateWaiting(
                            reason="ContainerCreating"))))
                continue
            if rc.state == ContainerState.RUNNING:
                n_running += 1
                ready = self.prober_manager.is_ready(uid, container.name)
                statuses.append(api.ContainerStatus(
                    name=container.name, image=rc.image, ready=ready,
                    restart_count=rc.restart_count, container_id=rc.id,
                    state=api.ContainerState(
                        running=api.ContainerStateRunning(
                            started_at=_rfc3339(rc.started_at)))))
            else:
                if rc.exit_code == 0:
                    n_succeeded += 1
                else:
                    n_failed += 1
                statuses.append(api.ContainerStatus(
                    name=container.name, image=rc.image,
                    restart_count=rc.restart_count, container_id=rc.id,
                    state=api.ContainerState(
                        terminated=api.ContainerStateTerminated(
                            exit_code=rc.exit_code,
                            message=rc.message,
                            started_at=(_rfc3339(rc.started_at)
                                        if rc.started_at else ""),
                            finished_at=(_rfc3339(rc.finished_at)
                                         if rc.finished_at else "")))))
        phase = self._pod_phase(pod, len(pod.spec.containers), n_running,
                                n_succeeded, n_failed)
        all_ready = (phase == api.POD_RUNNING
                     and all(s.ready for s in statuses))
        start_time = (pod.status.start_time
                      or self._start_times.setdefault(uid,
                                                      api.now_rfc3339()))
        status = api.PodStatus(
            phase=phase,
            conditions=[api.PodCondition(
                type="Ready", status="True" if all_ready else "False")],
            host_ip="10.0.0.1",
            pod_ip=self._pod_ip(pod),
            start_time=start_time,
            container_statuses=statuses)
        self.status_manager.set_pod_status(pod, status)

    def _pod_ip(self, pod: api.Pod) -> str:
        """The plugin-reported IP overrides what the runtime/apiserver
        carries (plugins.go:63-66 PodNetworkStatus note); cached per
        pod — the reference polls Status at intervals, not per
        publish."""
        uid = pod.metadata.uid
        if self.network_plugin is not None and uid in self._networked:
            with self._lock:
                cached = self._pod_ips.get(uid)
            if cached:
                return cached
            try:
                ip = self.network_plugin.status(
                    pod.metadata.namespace, pod.metadata.name, uid)
            except Exception:
                ip = None
            if ip:
                with self._lock:
                    self._pod_ips[uid] = ip
                return ip
        return pod.status.pod_ip or PLACEHOLDER_POD_IP

    @staticmethod
    def _pod_phase(pod: api.Pod, total: int, running: int, succeeded: int,
                   failed: int) -> str:
        """(ref: kubelet.go getPhase — note Always NEVER yields a
        terminal phase: its containers are about to restart)"""
        policy = pod.spec.restart_policy
        if total == 0:
            return api.POD_PENDING
        if running > 0:
            return api.POD_RUNNING
        if succeeded + failed == total:  # all terminated
            if policy == "Always":
                return api.POD_RUNNING  # restarts imminent
            if policy == "OnFailure":
                return (api.POD_SUCCEEDED if failed == 0
                        else api.POD_RUNNING)
            return api.POD_FAILED if failed else api.POD_SUCCEEDED
        return api.POD_PENDING

    # -------------------------------------------------------- sync loop

    def _sync_loop(self) -> None:
        """(kubelet.go:2277 syncLoop — PLEG events + periodic resync +
        housekeeping on one thread; pod updates arrive via the informer
        handlers, which dispatch straight to pod workers)"""
        last_sync = last_housekeeping = time.time()
        while not self._stop.is_set():
            try:
                event = self.pleg.events.get(timeout=0.2)
            except queue.Empty:
                event = None
            if event is not None:
                pod = self._pods.get(event.pod_uid)
                if pod is not None:
                    self._worker_for(pod).update(pod)
            now = time.time()
            if now - last_sync >= SYNC_PERIOD:
                last_sync = now
                with self._lock:
                    pods = list(self._pods.values())
                for pod in pods:
                    self._worker_for(pod).update(pod)
            if now - last_housekeeping >= HOUSEKEEPING_PERIOD:
                last_housekeeping = now
                try:
                    self._housekeeping()
                except Exception:
                    # one transient runtime error must not kill the
                    # kubelet's only sync/housekeeping thread (the
                    # reference wraps syncLoop work in HandleCrash)
                    logger.warning("housekeeping pass failed; retrying "
                                   "next period", exc_info=True)

    def _housekeeping(self) -> None:
        """Kill runtime pods whose API object is gone, tear down their
        orphaned volume dirs (kubelet.go HandlePodCleanups +
        cleanupOrphanedPodDirs), and prune dead containers on runtimes
        that accumulate them (dockertools/container_gc.go)."""
        now = time.time()
        with self._lock:
            known = set(self._pods)
        if (self._container_gc is not None or self._pod_gc) and \
                now - self._last_container_gc >= CONTAINER_GC_PERIOD:
            self._last_container_gc = now
            try:
                if self._container_gc is not None:
                    self._container_gc.garbage_collect()
                else:
                    # desired pods are never swept, even when their
                    # unit is between generations (see cli_runtime
                    # garbage_collect)
                    self.runtime.garbage_collect(keep_uids=known)
            except Exception:
                pass  # next pass retries
            # GC can be slow (CLI execs): re-snapshot so pods bound
            # meanwhile aren't killed as orphans below
            with self._lock:
                known = set(self._pods)
        with self._lock:
            tearing = set(self._tearing_down)
        for rp in self.runtime.get_pods():
            if rp.uid not in known and rp.uid not in tearing:
                # mid-teardown pods are the deletion thread's to kill —
                # sweeping them here would race a running PreStop hook
                self.runtime.kill_pod(rp.uid)
        if self.volume_mgr is not None:
            with self._lock:
                orphaned = self._mounted - known
            for uid in orphaned:
                try:
                    self.volume_mgr.tear_down_orphaned(uid)
                except Exception:
                    continue  # stays tracked: next pass retries
                with self._lock:
                    self._mounted.discard(uid)
        if self.network_plugin is not None:
            with self._lock:
                net_orphaned = {u: nn for u, nn in self._networked.items()
                                if u not in known}
            for uid, (ns, name) in net_orphaned.items():
                try:
                    self.network_plugin.tear_down_pod(ns, name, uid)
                except Exception:
                    continue  # stays tracked: next pass retries
                with self._lock:
                    self._networked.pop(uid, None)
                    self._pod_ips.pop(uid, None)
        if self.shaper is not None:
            self._cleanup_bandwidth_limits()

    def _cleanup_bandwidth_limits(self) -> None:
        """Drop shaping for CIDRs no pod owns anymore (kubelet.go:1826
        cleanupBandwidthLimits)."""
        from .bandwidth import extract_pod_bandwidth
        try:
            current = self.shaper.get_cidrs()
        except Exception:
            return
        possible = set()
        with self._lock:
            pods = list(self._pods.values())
            ips = dict(self._pod_ips)
        for pod in pods:
            try:
                ingress, egress = extract_pod_bandwidth(pod)
            except ValueError:
                continue
            if ingress is None and egress is None:
                continue
            ip = ips.get(pod.metadata.uid) or pod.status.pod_ip
            if ip:
                possible.add(f"{ip}/32")
        with self._lock:
            for uid in set(self._shaped) - set(
                    p.metadata.uid for p in pods):
                self._shaped.pop(uid, None)
        for cidr in current:
            if cidr not in possible:
                try:
                    self.shaper.reset(cidr)
                except Exception:
                    pass  # next pass retries

    # -------------------------------------------------------- lifecycle

    def run(self) -> "Kubelet":
        self.status_manager.start()
        self.pleg.start()
        # cgroup-role memory-limit enforcement for runtimes with live
        # /proc stats (subprocess runtime); fakes lack container_stats
        # and skip it (ref: pkg/kubelet/cm's cgroup limits)
        self._enforcer = None
        if hasattr(self.runtime, "container_stats"):
            from .cm import ResourceEnforcer

            def bound_pods():
                with self._lock:
                    return list(self._pods.values())

            self._enforcer = ResourceEnforcer(
                self.runtime, bound_pods,
                on_oom=self._on_oom_kill).start()
        # services BEFORE pods (kubelet.go:245 starts the service watch
        # at construction): a pod synced ahead of the service cache
        # would start its containers with an empty service-env
        # projection, and env is never recomputed for a running
        # container. All namespaces: the per-pod-namespace projection
        # happens at env construction (envvars.service_env_map).
        self._service_informer = Informer(self.client, "services").start()
        deadline = time.time() + 5.0
        while (not self._service_informer.has_synced
               and time.time() < deadline):
            time.sleep(0.01)
        self._informer = Informer(
            self.client, "pods",
            field_selector=f"spec.nodeName={self.node_name}",
            on_add=self.handle_pod_addition,
            on_update=self.handle_pod_update,
            on_delete=self.handle_pod_deletion).start()
        if self.manifest_path or self.manifest_url:
            # static-pod sources merge with the apiserver stream
            # (pkg/kubelet/config PodConfig mux)
            from .config import FileSource, HTTPSource, PodConfig
            pod_config = PodConfig(self.handle_pod_addition,
                                   self.handle_pod_update,
                                   self.handle_pod_deletion)
            if self.manifest_path:
                self._sources.append(FileSource(
                    pod_config, self.node_name,
                    self.manifest_path).start())
            if self.manifest_url:
                self._sources.append(HTTPSource(
                    pod_config, self.node_name,
                    self.manifest_url).start())
        t = threading.Thread(target=self._sync_loop, daemon=True,
                             name=f"kubelet-{self.node_name}")
        t.start()
        self._threads = [t]
        return self

    def _on_oom_kill(self, pod_uid: str, container: str, used: int,
                     limit: int) -> None:
        """An enforcement kill surfaces like cgroup OOM: the PLEG sees
        the exit and the restart policy decides; the status trail says
        why."""
        logger.warning(
            "memory limit exceeded: pod %s container %s used %d > %d",
            pod_uid, container, used, limit)

    def stop(self) -> None:
        self._stop.set()
        if getattr(self, "_enforcer", None) is not None:
            self._enforcer.stop()
        if self._informer:
            self._informer.stop()
        if self._service_informer:
            self._service_informer.stop()
        for source in self._sources:
            source.stop()
        self.pleg.stop()
        self.prober_manager.stop()
        self.status_manager.stop()
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for w in workers:
            w.stop()
