"""Kubelet: the node agent's full core.

Reference: pkg/kubelet — syncLoop/syncLoopIteration (kubelet.go:2277,
2297: select over pod updates | PLEG events | housekeeping), per-pod
workers (pod_workers.go:105,137), PLEG (pleg/generic.go:78,102), probers
(prober/{manager,worker,prober}.go + pkg/probe executors), status
manager (status/manager.go:117-146), and the container Runtime interface
(pkg/kubelet/container) with a fake runtime standing in for the docker
manager (dockertools/manager.go) the way kubemark's FakeDockerClient
does. agents.hollow_node.HollowKubelet remains the thin hollow variant;
this package is the real sync machinery.
"""

from .container import (ContainerState, FakeRuntime, Runtime,
                        RuntimeContainer, RuntimePod)
from .pleg import GenericPLEG, PodLifecycleEvent
from .prober import Prober, ProberManager, ProbeResult
from .kubelet import Kubelet

__all__ = [
    "ContainerState", "FakeRuntime", "Runtime", "RuntimeContainer",
    "RuntimePod", "GenericPLEG", "PodLifecycleEvent", "Prober",
    "ProberManager", "ProbeResult", "Kubelet",
]
