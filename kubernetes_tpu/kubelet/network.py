"""Kubelet network plugins.

The reference kubelet delegates pod network setup/teardown/status to a
named plugin (ref: pkg/kubelet/network/plugins.go NetworkPlugin —
Init/SetUpPod/TearDownPod/Status, PodNetworkStatus carrying the pod IP
that overrides what the runtime reports) with an executable-script
implementation (ref: pkg/kubelet/network/exec/exec.go: run
``<dir>/<name>/<name> init|setup|teardown|status`` with
``<pod_namespace> <pod_name> <container_id>``; status prints a
PodNetworkStatus JSON; vendored names escape ``/`` as ``~``).

Here the same seam carries two implementations: the exec plugin with
the reference's exact argv/JSON contract, and a loopback plugin — the
truthful default for subprocess pods, which share the host network
namespace and are reachable on 127.0.0.1 (so portforward, the
apiserver pod proxy, and downward-API status.podIP all work against
real addresses).
"""

from __future__ import annotations

import json
import os
import subprocess
from typing import Optional


class NetworkPlugin:
    """(plugins.go:44 NetworkPlugin)"""

    name = ""

    def init(self) -> None:
        pass

    def set_up_pod(self, namespace: str, name: str, pod_id: str) -> None:
        raise NotImplementedError

    def tear_down_pod(self, namespace: str, name: str,
                      pod_id: str) -> None:
        raise NotImplementedError

    def status(self, namespace: str, name: str,
               pod_id: str) -> Optional[str]:
        """The pod's primary IP, or None to defer to the runtime
        (exec.go status contract)."""
        raise NotImplementedError


class HostNetworkPlugin(NetworkPlugin):
    """Process pods live in the host network namespace, so their
    reachable address IS the node's own (the plugins.go no-op default
    with a truthful Status — unlike a placeholder, this address works
    from other nodes too: endpoints/DNS/proxy built from it route to
    the host the processes actually listen on)."""

    name = "host"
    # pods do NOT own unique addresses: per-pod address-keyed features
    # (bandwidth shaping on ip/32) must treat them like host-network
    # pods or they'd program the node's own address
    shared_host_address = True

    def __init__(self, node_ip: str = "127.0.0.1"):
        self.node_ip = node_ip

    def set_up_pod(self, namespace, name, pod_id):
        pass

    def tear_down_pod(self, namespace, name, pod_id):
        pass

    def status(self, namespace, name, pod_id):
        return self.node_ip


class ExecNetworkPlugin(NetworkPlugin):
    """Shell out to the operator's plugin executable (exec.go:105-170).

    plugin_name may be vendored ("mycompany/mysdn" →
    ``mycompany~mysdn/mysdn``)."""

    def __init__(self, plugin_dir: str, plugin_name: str,
                 timeout: float = 30.0):
        self.name = plugin_name
        escaped = plugin_name.replace("/", "~")
        base = plugin_name.rsplit("/", 1)[-1]
        self.exec_path = os.path.join(plugin_dir, escaped, base)
        self.timeout = timeout

    def _run(self, *args: str) -> str:
        out = subprocess.run(
            [self.exec_path, *args], capture_output=True, text=True,
            timeout=self.timeout)
        if out.returncode != 0:
            raise RuntimeError(
                f"network plugin {self.name!r} {args[0]}: "
                f"rc={out.returncode} {out.stdout}{out.stderr}".strip())
        return out.stdout

    def init(self) -> None:
        self._run("init")

    def set_up_pod(self, namespace, name, pod_id):
        self._run("setup", namespace, name, pod_id)

    def tear_down_pod(self, namespace, name, pod_id):
        self._run("teardown", namespace, name, pod_id)

    def status(self, namespace, name, pod_id):
        out = self._run("status", namespace, name, pod_id).strip()
        if not out:
            return None  # defer to the runtime (exec.go:152-156)
        doc = json.loads(out)
        kind = doc.get("kind", "")
        if kind and kind != "PodNetworkStatus":
            raise ValueError(
                f"invalid kind {kind!r} in network status for pod "
                f"{name!r} (want PodNetworkStatus)")
        ip = doc.get("ip", "")
        return ip or None
