"""Image manager: pull policies + LRU image garbage collection.

Reference: pkg/kubelet/container/image_puller.go (EnsureImageExists —
pull-policy dispatch, back-to-back pull throttling is out of hollow
scope) and pkg/kubelet/image_manager.go (disk-threshold LRU GC). The
runtime seam is a `puller(image) -> None` callable (the docker-pull HTTP
call in the reference; instant success for hollow nodes, a no-op for the
subprocess runtime whose "images" are argv[0] binaries).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class ImageNeverPullError(Exception):
    """(ref: image_puller.go ErrImageNeverPull)"""


def default_pull_policy(image: str, explicit: str) -> str:
    """:latest (or untagged) images default to Always, the rest to
    IfNotPresent (ref: pkg/api/v1/defaults.go SetDefaults_Container)."""
    if explicit:
        return explicit
    tag = image.rsplit(":", 1)[1] if ":" in image.split("/")[-1] else ""
    return "Always" if tag in ("", "latest") else "IfNotPresent"


class ImageManager:
    def __init__(self, puller: Optional[Callable[[str], None]] = None,
                 recorder=None):
        self.puller = puller or (lambda image: None)
        self.recorder = recorder
        # the puller seam takes (image) or (image, pod): the pod form
        # lets a runtime-backed puller resolve imagePullSecrets into a
        # registry credential (kubelet/credentialprovider.py). An
        # explicit `takes_pod` attribute on the puller wins (set by
        # runtime_puller; survives wrappers that forward it); arity
        # inference is only the fallback, and counts REQUIRED
        # positional params so an optional second arg (retries=3, a
        # bound keyring slot) never receives a Pod. *args wrappers
        # without the attribute infer takes_pod=False — wrap with
        # functools.wraps-style attribute forwarding or set the flag.
        explicit = getattr(puller, "takes_pod", None) \
            if puller is not None else None
        if explicit is not None:
            self._puller_takes_pod = bool(explicit)
        else:
            import inspect
            try:
                params = inspect.signature(
                    self.puller).parameters.values()
                required = [p for p in params
                            if p.default is inspect.Parameter.empty
                            and p.kind in (p.POSITIONAL_ONLY,
                                           p.POSITIONAL_OR_KEYWORD)]
                self._puller_takes_pod = len(required) >= 2
            except (TypeError, ValueError):
                self._puller_takes_pod = False
        self._lock = threading.Lock()
        self._present: Dict[str, float] = {}  # image -> last-used ts

    def is_present(self, image: str) -> bool:
        with self._lock:
            return image in self._present

    def ensure_image_exists(self, pod, container) -> None:
        """(ref: image_puller.go EnsureImageExists)"""
        image = container.image
        policy = default_pull_policy(image, container.image_pull_policy)
        with self._lock:
            present = image in self._present
            if present:
                self._present[image] = time.time()
        if policy == "Never":
            # never pulls, whether or not the image is present (the
            # reference's shouldPullImage is unconditionally false for
            # PullNever, image_puller.go); absent is the start error
            if not present:
                raise ImageNeverPullError(
                    f"container {container.name}: image {image!r} is not "
                    f"present with pull policy of Never")
            return
        if policy == "IfNotPresent" and present:
            return
        if self._puller_takes_pod:
            self.puller(image, pod)
        else:
            self.puller(image)
        if self.recorder is not None:
            self.recorder.eventf(pod, "Normal", "Pulled",
                                 f"Successfully pulled image {image!r}")
        with self._lock:
            self._present[image] = time.time()

    def images(self):
        with self._lock:
            return dict(self._present)

    def garbage_collect(self, usage_percent: float,
                        high_threshold: float = 90.0,
                        low_threshold: float = 80.0,
                        remover: Optional[Callable[[str], None]] = None
                        ) -> int:
        """Evict least-recently-used images until usage is projected
        under the low threshold (ref: image_manager.go GarbageCollect —
        thresholds are --image-gc-high-threshold/-low-threshold). Each
        evicted image is assumed to free an equal share of usage, the
        hollow stand-in for byte sizes."""
        if usage_percent < high_threshold:
            return 0
        evicted = []
        with self._lock:
            by_age = sorted(self._present.items(), key=lambda kv: kv[1])
            if not by_age:
                return 0
            share = usage_percent / len(by_age)
            while by_age and \
                    usage_percent - len(evicted) * share > low_threshold:
                image, _ = by_age.pop(0)
                del self._present[image]
                evicted.append(image)
        # removers run OUTSIDE the lock: they may be slow or call back
        # into this manager
        if remover is not None:
            for image in evicted:
                remover(image)
        return len(evicted)
