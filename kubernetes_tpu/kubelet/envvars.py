"""Service-discovery env vars, $(var) expansion, and field-path values.

Every container starts with environment variables locating every
service visible to its pod — the `{NAME}_SERVICE_HOST` /
`{NAME}_SERVICE_PORT` pairs plus the docker-links-compatible
`{NAME}_PORT_*` family (ref: pkg/kubelet/envvars/envvars.go:31-108
FromServices), projected by namespace the way the reference kubelet
does it (ref: pkg/kubelet/kubelet.go:1340-1390 getServiceEnvVarMap: the
pod's own namespace plus the master "kubernetes" service). Declared
values run through the reference's `$(VAR)` expansion algorithm (ref:
third_party/golang/expansion/expand.go) and `valueFrom.fieldRef`
resolves downward-API field paths (ref: pkg/kubelet/kubelet.go:1453
podFieldSelectorRuntimeValue; pkg/fieldpath/fieldpath.go:38
ExtractFieldPathAsString).

Deliberate divergence: the reference emits the residual service vars in
Go-map iteration order (nondeterministic); here they are sorted by
service name so container environments are bit-reproducible — the same
determinism stance the device engine takes on tie-breaks.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

from ..core import types as api

# services in this namespace that every pod sees regardless of its own
# namespace (kubelet.go:1338 masterServices)
MASTER_SERVICES = ("kubernetes",)


def _mangle(name: str) -> str:
    # (envvars.go:66 makeEnvVariableName)
    return name.upper().replace("-", "_")


def _has_cluster_ip(svc: api.Service) -> bool:
    # headless or unallocated services produce no env vars
    # (envvars.go:38-42; api.IsServiceIPSet)
    return bool(svc.spec.cluster_ip) and svc.spec.cluster_ip != "None"


def from_services(services: Iterable[api.Service]) -> List[api.EnvVar]:
    """The env-var list for one container, given its visible services
    (envvars.go:31 FromServices)."""
    result: List[api.EnvVar] = []
    for svc in services:
        if not _has_cluster_ip(svc) or not svc.spec.ports:
            continue
        prefix = _mangle(svc.metadata.name)
        result.append(api.EnvVar(name=prefix + "_SERVICE_HOST",
                                 value=svc.spec.cluster_ip))
        # first port gets the backwards-compatible name; named ports get
        # a suffixed variant (only the first may be unnamed)
        port_name = prefix + "_SERVICE_PORT"
        result.append(api.EnvVar(name=port_name,
                                 value=str(svc.spec.ports[0].port)))
        for sp in svc.spec.ports:
            if sp.name:
                result.append(api.EnvVar(
                    name=port_name + "_" + _mangle(sp.name),
                    value=str(sp.port)))
        result.extend(_link_vars(prefix, svc))
    return result


def _link_vars(prefix: str, svc: api.Service) -> List[api.EnvVar]:
    """Docker-compatible link variables (envvars.go:75-108
    makeLinkVariables)."""
    out: List[api.EnvVar] = []
    ip = svc.spec.cluster_ip
    for i, sp in enumerate(svc.spec.ports):
        proto = sp.protocol or "TCP"
        url = f"{proto.lower()}://{ip}:{sp.port}"
        if i == 0:
            # docker special-cases the first port
            out.append(api.EnvVar(name=prefix + "_PORT", value=url))
        pp = f"{prefix}_PORT_{sp.port}_{proto.upper()}"
        out.append(api.EnvVar(name=pp, value=url))
        out.append(api.EnvVar(name=pp + "_PROTO", value=proto.lower()))
        out.append(api.EnvVar(name=pp + "_PORT", value=str(sp.port)))
        out.append(api.EnvVar(name=pp + "_ADDR", value=ip))
    return out


def service_env_map(services: Iterable[api.Service], namespace: str,
                    master_service_namespace: str = "default"
                    ) -> Dict[str, str]:
    """Project the cluster's services onto what a pod in ``namespace``
    should see (kubelet.go:1341 getServiceEnvVarMap): everything in its
    own namespace, plus the master services from the master namespace —
    with the pod-namespace definition winning a name collision."""
    chosen: Dict[str, api.Service] = {}
    for svc in services:
        if not _has_cluster_ip(svc):
            continue
        name = svc.metadata.name
        if svc.metadata.namespace == namespace:
            chosen[name] = svc  # always wins (kubelet.go:1371-1373)
        elif (svc.metadata.namespace == master_service_namespace
              and name in MASTER_SERVICES):
            chosen.setdefault(name, svc)
    ordered = sorted(chosen.values(), key=lambda s: s.metadata.name)
    return {e.name: e.value for e in from_services(ordered)}


def expand(value: str, *maps: Dict[str, str]) -> str:
    """``$(VAR)`` expansion (third_party/golang/expansion/expand.go):
    ``$$`` escapes to ``$``, earlier maps shadow later ones, and an
    unresolvable reference is left intact."""
    buf: List[str] = []
    i, n = 0, len(value)
    while i < n:
        ch = value[i]
        if ch == "$" and i + 1 < n:
            nxt = value[i + 1]
            if nxt == "$":
                buf.append("$")
                i += 2
                continue
            if nxt == "(":
                close = value.find(")", i + 2)
                if close != -1:
                    name = value[i + 2:close]
                    for m in maps:
                        if name in m:
                            buf.append(m[name])
                            break
                    else:
                        buf.append(value[i:close + 1])
                    i = close + 1
                    continue
                # incomplete reference: "$(" passes through literally
                buf.append("$(")
                i += 2
                continue
            # operator not starting an expression: both chars literal
            buf.append("$" + nxt)
            i += 2
            continue
        buf.append(ch)
        i += 1
    return "".join(buf)


def _format_map(m: Dict[str, str]) -> str:
    # (fieldpath.go:28 formatMap — %q quoting so embedded quotes,
    # backslashes and newlines can't forge extra key=value lines;
    # sorted here for reproducibility where Go map order is random)
    import json
    return "".join(f"{k}={json.dumps(v)}\n" for k, v in sorted(m.items()))


def extract_field_path(pod: api.Pod, field_path: str) -> str:
    """Downward-API field paths for env (kubelet.go:1453
    podFieldSelectorRuntimeValue + fieldpath.go:38)."""
    if field_path == "status.podIP":
        return pod.status.pod_ip
    if field_path == "metadata.name":
        return pod.metadata.name
    if field_path == "metadata.namespace":
        return pod.metadata.namespace
    if field_path == "metadata.labels":
        return _format_map(pod.metadata.labels)
    if field_path == "metadata.annotations":
        return _format_map(pod.metadata.annotations)
    raise ValueError(f"unsupported fieldPath: {field_path}")


def make_environment(pod: api.Pod, container: api.Container,
                     services: Iterable[api.Service],
                     master_service_namespace: str = "default"
                     ) -> List[api.EnvVar]:
    """The final environment for one container start (kubelet.go:1393
    makeEnvironmentVariables): declared vars in declaration order —
    values expanded against earlier declarations then service env,
    ``fieldRef`` sources resolved — followed by the remaining service
    vars (sorted; see module docstring)."""
    service_env = service_env_map(services, pod.metadata.namespace,
                                  master_service_namespace)
    tmp_env: Dict[str, str] = {}
    result: List[api.EnvVar] = []
    for ev in container.env:
        # a declared var shadows the generated service var outright
        # (kubelet.go:1428 delete(serviceEnv, envVar.Name))
        service_env.pop(ev.name, None)
        runtime_val = ev.value
        if runtime_val:
            runtime_val = expand(runtime_val, tmp_env, service_env)
        elif ev.value_from is not None and ev.value_from.field_ref is not None:
            runtime_val = extract_field_path(
                pod, ev.value_from.field_ref.field_path)
        tmp_env[ev.name] = runtime_val
        result.append(api.EnvVar(name=ev.name, value=runtime_val))
    for name in sorted(service_env):
        result.append(api.EnvVar(name=name, value=service_env[name]))
    return result
