"""Resource stats — the cadvisor analogue.

Reference: pkg/kubelet/cadvisor (wraps github.com/google/cadvisor reading
cgroupfs) feeding the kubelet's /stats endpoints (pkg/kubelet/server.go),
with cadvisor.Fake for kubemark hollow nodes. Here the same split:
`ProcStatsProvider` reads the real /proc for node-level CPU/memory (the
runtime supplies per-pod numbers when it can — the subprocess runtime
reads its children's /proc), and `FakeStatsProvider` produces
deterministic synthetic stats for hollow fleets.

The wire shape follows the summary API (NodeStats/PodStats/
ContainerStats) the reference's /stats/summary serves.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class ContainerStats:
    name: str = ""
    cpu_usage_nano_cores: int = 0
    memory_working_set_bytes: int = 0
    restart_count: int = 0


@dataclass
class PodStats:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    containers: List[ContainerStats] = field(default_factory=list)


@dataclass
class NodeStats:
    node_name: str = ""
    cpu_usage_nano_cores: int = 0
    memory_total_bytes: int = 0
    memory_available_bytes: int = 0
    memory_working_set_bytes: int = 0
    fs_capacity_bytes: int = 0
    fs_available_bytes: int = 0
    start_time: float = 0.0


@dataclass
class Summary:
    """(ref: the /stats/summary response shape)"""
    node: NodeStats = field(default_factory=NodeStats)
    pods: List[PodStats] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "node": {
                "nodeName": self.node.node_name,
                "cpu": {"usageNanoCores": self.node.cpu_usage_nano_cores},
                "memory": {
                    "totalBytes": self.node.memory_total_bytes,
                    "availableBytes": self.node.memory_available_bytes,
                    "workingSetBytes": self.node.memory_working_set_bytes},
                "fs": {"capacityBytes": self.node.fs_capacity_bytes,
                       "availableBytes": self.node.fs_available_bytes},
                "startTime": self.node.start_time},
            "pods": [{
                "podRef": {"name": p.name, "namespace": p.namespace,
                           "uid": p.uid},
                "containers": [{
                    "name": c.name,
                    "cpu": {"usageNanoCores": c.cpu_usage_nano_cores},
                    "memory": {
                        "workingSetBytes": c.memory_working_set_bytes},
                    "restartCount": c.restart_count}
                    for c in p.containers]}
                for p in self.pods]}


class StatsProvider:
    """Interface: summary(node_name, pods, runtime) -> Summary."""

    def summary(self, node_name: str, pods, runtime) -> Summary:
        raise NotImplementedError


def _pod_container_stats(pods, runtime) -> List[PodStats]:
    """Per-pod stats from the runtime's view; runtimes that can meter
    their containers expose container_stats(pod_uid, name) -> dict."""
    out = []
    meter = getattr(runtime, "container_stats", None)
    by_uid = {rp.uid: rp for rp in runtime.get_pods()}
    for pod in pods:
        ps = PodStats(name=pod.metadata.name,
                      namespace=pod.metadata.namespace,
                      uid=pod.metadata.uid)
        rp = by_uid.get(pod.metadata.uid)
        for c in (rp.containers if rp is not None else []):
            cs = ContainerStats(name=c.name,
                                restart_count=c.restart_count)
            if meter is not None:
                m = meter(rp.uid, c.name) or {}
                cs.cpu_usage_nano_cores = int(
                    m.get("cpu_usage_nano_cores", 0))
                cs.memory_working_set_bytes = int(
                    m.get("memory_working_set_bytes", 0))
            ps.containers.append(cs)
        out.append(ps)
    return out


class ProcStatsProvider(StatsProvider):
    """Real node stats from /proc (the cgroupfs-reading role of cadvisor;
    node-level only — per-container metering belongs to the runtime)."""

    def __init__(self):
        self._start = time.time()
        self._last_cpu: Optional[tuple] = None  # (ts, busy_jiffies)

    @staticmethod
    def _read_proc_stat_busy() -> int:
        with open("/proc/stat") as f:
            fields = f.readline().split()[1:]
        vals = [int(v) for v in fields]
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
        return sum(vals) - idle

    def _cpu_nano_cores(self) -> int:
        """Busy jiffies per wall second -> nanocores (USER_HZ=100)."""
        now = time.time()
        busy = self._read_proc_stat_busy()
        last, self._last_cpu = self._last_cpu, (now, busy)
        if last is None or now <= last[0]:
            return 0
        cores = (busy - last[1]) / 100.0 / (now - last[0])
        return int(cores * 1e9)

    @staticmethod
    def _meminfo() -> Dict[str, int]:
        out = {}
        with open("/proc/meminfo") as f:
            for line in f:
                name, _, rest = line.partition(":")
                out[name] = int(rest.split()[0]) * 1024
        return out

    def summary(self, node_name: str, pods, runtime) -> Summary:
        mem = self._meminfo()
        st = os.statvfs("/")
        total = mem.get("MemTotal", 0)
        avail = mem.get("MemAvailable", mem.get("MemFree", 0))
        node = NodeStats(
            node_name=node_name,
            cpu_usage_nano_cores=self._cpu_nano_cores(),
            memory_total_bytes=total,
            memory_available_bytes=avail,
            memory_working_set_bytes=total - avail,
            fs_capacity_bytes=st.f_blocks * st.f_frsize,
            fs_available_bytes=st.f_bavail * st.f_frsize,
            start_time=self._start)
        return Summary(node=node, pods=_pod_container_stats(pods, runtime))


class FakeStatsProvider(StatsProvider):
    """(ref: cadvisor.Fake — fixed synthetic machine stats so hollow
    fleets serve /stats without touching the host)"""

    def __init__(self, cpu_nano_cores: int = 250_000_000,
                 memory_total: int = 32 << 30):
        self.cpu_nano_cores = cpu_nano_cores
        self.memory_total = memory_total
        self._start = time.time()

    def summary(self, node_name: str, pods, runtime) -> Summary:
        node = NodeStats(
            node_name=node_name,
            cpu_usage_nano_cores=self.cpu_nano_cores,
            memory_total_bytes=self.memory_total,
            memory_available_bytes=self.memory_total // 2,
            memory_working_set_bytes=self.memory_total // 2,
            fs_capacity_bytes=100 << 30,
            fs_available_bytes=50 << 30,
            start_time=self._start)
        return Summary(node=node, pods=_pod_container_stats(pods, runtime))
