"""Security-context application at the runtime boundary.

Reference: pkg/securitycontext/provider.go — SimpleSecurityContext
Provider.ModifyContainerConfig (RunAsUser -> config.User) and
ModifyHostConfig (Privileged, Capabilities Add/Drop -> HostConfig).
The admission side (SecurityContextDeny) polices these fields; this
module is the half that actually programs them into the engine's
container-create payload. The subprocess runtime applies what a
process CAN honor (it refuses privileged — there is no privileged
process mode to grant)."""

from __future__ import annotations

from typing import Optional

from ..core import types as api


def effective_privileged(container: api.Container) -> bool:
    """The flat pre-SecurityContext field OR the nested one — the
    reference reads SecurityContext.Privileged; the flat field stayed
    for wire compat with earlier rounds' objects."""
    if container.privileged:
        return True
    sc = container.security_context
    return bool(sc is not None and sc.privileged)


def apply_to_container_config(container: api.Container,
                              config: dict) -> None:
    """(provider.go ModifyContainerConfig). run_as_non_root is
    ENFORCED here, not silently carried: without image inspection the
    only verifiable non-root assertion is an explicit nonzero
    run_as_user — anything else must refuse to start (the
    fail-closed reading of the later reference's VerifyNonRoot)."""
    sc = container.security_context
    if sc is not None and sc.run_as_user is not None:
        config["User"] = str(sc.run_as_user)
    if sc is not None and sc.run_as_non_root:
        if sc.run_as_user is None:
            raise ValueError(
                f"container {container.name!r}: runAsNonRoot requires "
                f"an explicit runAsUser (image users are not "
                f"inspectable here)")
        if sc.run_as_user == 0:
            raise ValueError(
                f"container {container.name!r}: runAsNonRoot with "
                f"runAsUser=0 is contradictory")


def apply_to_host_config(container: api.Container,
                         host_config: dict) -> None:
    """(provider.go ModifyHostConfig)"""
    if effective_privileged(container):
        host_config["Privileged"] = True
    sc = container.security_context
    if sc is not None and sc.capabilities is not None:
        if sc.capabilities.add:
            host_config["CapAdd"] = list(sc.capabilities.add)
        if sc.capabilities.drop:
            host_config["CapDrop"] = list(sc.capabilities.drop)
