"""Container runtime abstraction + fake runtime.

Reference: pkg/kubelet/container (the Runtime interface, Pod/Container
runtime types) and dockertools/manager.go's SyncPod semantics, with the
fake playing FakeDockerClient's role (controllable from tests: kill a
container, fail the next start).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core import types as api


class ContainerState:
    RUNNING = "running"
    EXITED = "exited"


def tail_text(text: str, tail_lines: int) -> str:
    """Last N lines (0 = all) — the /containerLogs?tailLines contract,
    shared by every runtime."""
    if tail_lines > 0:
        return "".join(text.splitlines(keepends=True)[-tail_lines:])
    return text


@dataclass
class RuntimeContainer:
    """(ref: kubecontainer.Container)"""
    id: str = ""
    name: str = ""
    image: str = ""
    state: str = ContainerState.RUNNING
    started_at: float = 0.0
    finished_at: float = 0.0
    exit_code: int = 0
    restart_count: int = 0
    message: str = ""  # termination message read at exit


@dataclass
class RuntimePod:
    """(ref: kubecontainer.Pod)"""
    uid: str = ""
    name: str = ""
    namespace: str = ""
    containers: List[RuntimeContainer] = field(default_factory=list)


class Runtime:
    """(ref: kubecontainer.Runtime interface — the subset the sync loop
    and PLEG consume)"""

    def get_pods(self) -> List[RuntimePod]:
        raise NotImplementedError

    def start_container(self, pod: api.Pod, container: api.Container
                        ) -> RuntimeContainer:
        raise NotImplementedError

    def kill_container(self, pod_uid: str, name: str) -> None:
        raise NotImplementedError

    def kill_pod(self, pod_uid: str,
                 grace_seconds: Optional[float] = None) -> None:
        """grace_seconds bounds the TERM->KILL window per the pod's own
        grace period (ref: dockertools KillContainer receives the
        DeleteOptions/spec grace); None means the runtime's default.
        Runtimes without a graded stop may ignore it."""
        raise NotImplementedError

    def get_container_logs(self, pod_uid: str, name: str,
                           tail_lines: int = 0,
                           previous: bool = False) -> str:
        """(ref: kubecontainer.Runtime GetContainerLogs, served by the
        kubelet's /containerLogs endpoint, server.go:242; previous=True
        is the last terminated instance — kubectl logs -p)"""
        raise NotImplementedError

    def exec_in_container(self, pod_uid: str, name: str,
                          cmd: List[str]) -> Tuple[int, str]:
        """-> (exit_code, combined output) (ref: ExecInContainer)"""
        raise NotImplementedError

    def pod_port_address(self, pod_uid: str, port: int) -> Tuple[str, int]:
        """Where a pod's TCP port is reachable from this kubelet — the
        PortForward target (ref: kubecontainer.Runtime PortForward;
        dockertools resolves the pod's network namespace). Host-network
        runtimes answer ("127.0.0.1", port)."""
        raise NotImplementedError


class FakeRuntime(Runtime):
    """In-memory runtime: containers 'run' until told otherwise.

    Test controls: exit_container() simulates a crash (with exit code);
    fail_next_start() makes the next start raise — exercising the
    kubelet's backoff/retry paths.
    """

    def __init__(self):
        self._pods: Dict[str, RuntimePod] = {}
        self._lock = threading.Lock()
        self._fail_next = 0
        self._counter = 0
        self._logs: Dict[Tuple[str, str], str] = {}  # (uid, name) -> text
        self._port_addrs: Dict[Tuple[str, int], Tuple[str, int]] = {}

    # ----------------------------------------------------- Runtime API

    def get_pods(self) -> List[RuntimePod]:
        with self._lock:
            return [RuntimePod(uid=p.uid, name=p.name, namespace=p.namespace,
                               containers=[RuntimeContainer(**vars(c))
                                           for c in p.containers])
                    for p in self._pods.values()]

    def start_container(self, pod: api.Pod, container: api.Container
                        ) -> RuntimeContainer:
        with self._lock:
            if self._fail_next > 0:
                self._fail_next -= 1
                raise RuntimeError(f"start {container.name}: injected failure")
            rp = self._pods.setdefault(pod.metadata.uid, RuntimePod(
                uid=pod.metadata.uid, name=pod.metadata.name,
                namespace=pod.metadata.namespace))
            prior = [c for c in rp.containers if c.name == container.name]
            restart_count = max((c.restart_count for c in prior),
                                default=-1) + 1
            # the old instance's record is replaced, like docker rm
            rp.containers = [c for c in rp.containers
                             if c.name != container.name]
            self._counter += 1
            rc = RuntimeContainer(
                id=f"fake://{pod.metadata.uid}/{container.name}/{self._counter}",
                name=container.name, image=container.image,
                state=ContainerState.RUNNING, started_at=time.time(),
                restart_count=restart_count)
            rp.containers.append(rc)
            return rc

    def kill_container(self, pod_uid: str, name: str) -> None:
        # killed containers report 128+SIGKILL like docker (137)
        self._transition(pod_uid, name, exit_code=137)

    def kill_pod(self, pod_uid: str,
                 grace_seconds: Optional[float] = None) -> None:
        with self._lock:
            self._pods.pop(pod_uid, None)

    def get_container_logs(self, pod_uid: str, name: str,
                           tail_lines: int = 0,
                           previous: bool = False) -> str:
        if previous:
            raise KeyError('fake runtime keeps no previous logs')
        with self._lock:
            text = self._logs.get((pod_uid, name))
            if text is None:
                rp = self._pods.get(pod_uid)
                known = rp is not None and any(
                    c.name == name for c in rp.containers)
                if not known:
                    raise KeyError(f"container {name!r} not found")
                text = f"fake logs for {name}\n"
        return tail_text(text, tail_lines)

    def exec_in_container(self, pod_uid: str, name: str,
                          cmd: List[str]) -> Tuple[int, str]:
        with self._lock:
            rp = self._pods.get(pod_uid)
            if rp is None or not any(c.name == name for c in rp.containers):
                raise KeyError(f"container {name!r} not found")
        return 0, f"fake exec: {' '.join(cmd)}\n"

    # ------------------------------------------------- test controls

    def set_container_logs(self, pod_uid: str, name: str,
                           text: str) -> None:
        with self._lock:
            self._logs[(pod_uid, name)] = text

    def exit_container(self, pod_uid: str, name: str,
                       exit_code: int = 1) -> None:
        """Simulate a container crash."""
        self._transition(pod_uid, name, exit_code)

    def set_port_address(self, pod_uid: str, port: int,
                         addr: Tuple[str, int]) -> None:
        """Test control: where pod_port_address answers for (pod, port)
        — tests point it at a real local listener."""
        self._port_addrs[(pod_uid, port)] = addr

    def pod_port_address(self, pod_uid: str, port: int) -> Tuple[str, int]:
        try:
            return self._port_addrs[(pod_uid, port)]
        except KeyError:
            raise KeyError(f"pod {pod_uid!r} has nothing on port {port}")

    def fail_next_start(self, n: int = 1) -> None:
        with self._lock:
            self._fail_next += n

    def running_containers(self, pod_uid: str) -> List[str]:
        with self._lock:
            rp = self._pods.get(pod_uid)
            if rp is None:
                return []
            return [c.name for c in rp.containers
                    if c.state == ContainerState.RUNNING]

    # ------------------------------------------------------- helpers

    def _transition(self, pod_uid: str, name: str, exit_code: int) -> None:
        with self._lock:
            rp = self._pods.get(pod_uid)
            if rp is None:
                return
            for c in rp.containers:
                if c.name == name and c.state == ContainerState.RUNNING:
                    c.state = ContainerState.EXITED
                    c.finished_at = time.time()
                    c.exit_code = exit_code
