"""CLI/unit-file runtime: the kubelet driving a container CLI + unit
supervisor — the rkt process shape.

Reference: pkg/kubelet/rkt/rkt.go (1,534 LoC). Where the engine-daemon
adapter (daemon_runtime.py) is an HTTP CLIENT of a long-lived daemon,
this boundary is exec-a-CLI + systemd units, and it is POD-granular:

- one pod = one prepared CLI pod = one service unit. `prepare` turns
  the whole pod spec into an immutable prepared pod and returns its
  uuid (rkt.go:630 preparePod / makePodManifest :424); the unit's
  ExecStart is `<cli> run-prepared <uuid>` (rkt.go:694) and the unit
  file carries the kubernetes identity in an [X-Kubernetes] section
  (rkt.go:695-700 writes id/name/namespace as unit options).
- starting any container of a not-running pod (re)launches the WHOLE
  pod: the reference's SyncPod restarts the entire pod when any
  container needs a change (rkt.go:1156-1219 restartPod) because a
  prepared pod is immutable. The attempt counter therefore advances
  per POD generation and every app in a generation shares it.
- killing a container stops the whole unit (v1.1 rkt has no per-app
  kill; KillPod stops the unit after touching the service file so GC
  defers, rkt.go:982-1006). The restart policy revives the pod on the
  next sync.
- pod state is reconstructed from the unit files + the CLI's status
  (rkt.go:937 GetPods = read service files + rkt pod states); logs
  come from the unit journal (GetContainerLogs -> journalctl -u);
  exec is `<cli> enter` (rkt.go ExecInContainer); images are fetched
  with `<cli> fetch` (rkt.go:1093 PullImage — registry auth rides the
  CLI's own config dir, writeDockerAuthConfig :1049, not flags).
- GarbageCollect = reset-failed + remove inactive service files +
  per-uuid `<cli> gc` (rkt.go:1221-1260), min-age gated by the unit
  file mtime the stop path touches. The reference finishes with a
  global `rkt gc`; here collection is strictly per-uuid (at generation
  replacement, kill, and sweep) so kept corpses and pods mid-prepare
  are never reaped out from under the kubelet.

The CLI binary itself is the external runtime (rkt's role); tests run
the real adapter + real unit supervisor against a fake CLI the way the
daemon tests run a fake engine daemon.
"""

from __future__ import annotations

import json
import os
import re
import shlex
import subprocess
import time
from typing import Dict, Iterable, List, Optional, Tuple

from ..core import types as api
from .container import (ContainerState, Runtime, RuntimeContainer,
                        RuntimePod, tail_text)
from .unitd import ACTIVE, UnitManager

UNIT_PREFIX = "k8s_"  # makePodServiceFileName (rkt.go:214)
K8S_SECTION = "X-Kubernetes"
MIN_VERSION = (0, 8, 0)  # rkt.go:56 minimum binary version gate


def unit_name_for(pod_uid: str) -> str:
    """(ref: makePodServiceFileName rkt.go:214)"""
    return f"{UNIT_PREFIX}{pod_uid}.service"


def _should_restart(policy: str, exit_code: int) -> bool:
    """Per-app restart decision, mirrored from the kubelet's syncPod —
    the pod-granular runtime must apply it itself because a whole-pod
    restart re-runs EVERY app (rkt.go:1156 SyncPod consults the
    RestartPolicy per app before deciding to restartPod)."""
    if policy == "Never":
        return False
    if policy == "OnFailure":
        return exit_code != 0
    return True  # Always


class CliError(RuntimeError):
    def __init__(self, message: str, rc: int = 1, output: str = ""):
        super().__init__(message)
        self.rc = rc
        self.output = output


class CliRuntime(Runtime):
    """Runtime implemented over a container CLI + unit supervisor."""

    def __init__(self, cli: List[str], unit_dir: str,
                 min_version: Tuple[int, ...] = MIN_VERSION,
                 unit_manager: Optional[UnitManager] = None,
                 cli_timeout: float = 30.0,
                 status_cache_ttl: float = 0.5,
                 auth_dir: Optional[str] = None):
        self.cli = list(cli)
        self.units = unit_manager or UnitManager(unit_dir)
        self.auth_dir = auth_dir or os.path.join(unit_dir, "auth.d")
        self.cli_timeout = cli_timeout
        # every status read execs the CLI; the PLEG + status manager +
        # probers would stack subprocesses without a freshness window
        # (ref: pkg/kubelet/container/runtime_cache.go — the kubelet
        # caches GetPods with a TTL for exactly this reason)
        self._status_cache_ttl = status_cache_ttl
        self._status_cache: Dict[str, Tuple[float, Optional[dict]]] = {}
        # uuids whose per-uuid gc failed transiently; retried by the
        # next garbage_collect sweep (there is deliberately no global
        # gc to backstop them)
        self._orphan_uuids: set = set()
        # version gate at construction (rkt.go:132-183 New refuses to
        # run against a too-old binary or supervisor)
        ver = self.version()
        parsed = tuple(int(p) for p in ver.split("."))
        width = max(len(parsed), len(min_version))
        parsed += (0,) * (width - len(parsed))
        min_padded = tuple(min_version) + (0,) * (width - len(min_version))
        if parsed < min_padded:
            raise CliError(
                f"cli version {ver} older than required "
                f"{'.'.join(map(str, min_version))}")

    # ------------------------------------------------------------- wire

    def _run(self, *args: str, input_text: Optional[str] = None) -> str:
        """Exec the CLI; nonzero exit raises (ref: runCommand
        rkt.go:201-212)."""
        try:
            proc = subprocess.run(
                self.cli + list(args), input=input_text,
                capture_output=True, text=True, timeout=self.cli_timeout)
        except subprocess.TimeoutExpired as e:
            # a hung CLI must surface as a CliError like every other
            # failure: callers above (PLEG relist, housekeeping) treat
            # anything else as fatal to their threads
            raise CliError(f"{' '.join(args[:2])} timed out after "
                           f"{self.cli_timeout}s") from e
        if proc.returncode != 0:
            raise CliError(
                f"{' '.join(args[:2])} failed: "
                f"{(proc.stderr or proc.stdout).strip()[:300]}",
                rc=proc.returncode, output=proc.stdout)
        return proc.stdout

    def version(self) -> str:
        """Parse `<cli> version` (ref: rkt.go:1043 Version)."""
        out = self._run("version")
        m = re.search(r"Version:\s*([0-9]+(?:\.[0-9]+)*)", out)
        if not m:
            raise CliError(f"unparseable version output: {out[:120]!r}")
        return m.group(1)

    # ------------------------------------------------------ pod records

    def _records(self) -> List[dict]:
        """Every kubelet-owned unit file, parsed
        (ref: GetPods rkt.go:937 reads the service directory)."""
        out = []
        for name in self.units.unit_names():
            if not name.startswith(UNIT_PREFIX):
                continue  # foreign units are invisible to the kubelet
            rec = self._record(name)
            if rec is not None:
                out.append(rec)
        return out

    def _record(self, unit: str) -> Optional[dict]:
        try:
            opts = {(s, k): v for s, k, v in self.units.read_unit(unit)}
        except FileNotFoundError:
            return None
        uid = opts.get((K8S_SECTION, "POD_UID"))
        if not uid:
            return None
        return {
            "unit": unit, "uid": uid,
            "name": opts.get((K8S_SECTION, "POD_NAME"), ""),
            "namespace": opts.get((K8S_SECTION, "POD_NAMESPACE"), ""),
            "uuid": opts.get((K8S_SECTION, "PREPARED_UUID"), ""),
            "attempt": int(opts.get((K8S_SECTION, "ATTEMPT"), "0")),
        }

    def _record_for(self, pod_uid: str) -> Optional[dict]:
        unit = unit_name_for(pod_uid)
        if not self.units.has_unit(unit):
            return None
        return self._record(unit)

    def _status(self, uuid: str, fresh: bool = False) -> Optional[dict]:
        """App states for a prepared pod via `<cli> status`
        (ref: convertRktPod rkt.go:817 reads rkt's pod state). Served
        from the TTL cache unless fresh=True (runtime_cache.go role)."""
        if not fresh:
            cached = self._status_cache.get(uuid)
            if cached and time.time() - cached[0] < self._status_cache_ttl:
                return cached[1]
        try:
            out = self._run("status", uuid)
        except CliError:
            self._status_cache[uuid] = (time.time(), None)
            return None
        try:
            status = json.loads(out)
        except ValueError:
            status = None
        self._status_cache[uuid] = (time.time(), status)
        return status

    def _forget_status(self, uuid: str) -> None:
        self._status_cache.pop(uuid, None)

    # ---------------------------------------------------------- Runtime

    def get_pods(self) -> List[RuntimePod]:
        pods: List[RuntimePod] = []
        for rec in self._records():
            status = self._status(rec["uuid"]) or {"apps": {}}
            unit_active = self.units.unit_state(rec["unit"]) == ACTIVE
            rp = RuntimePod(uid=rec["uid"], name=rec["name"],
                            namespace=rec["namespace"])
            for app_name, app in status.get("apps", {}).items():
                running = app.get("state") == "running" and unit_active
                if app.get("state") == "running" and not unit_active:
                    # the unit died before the pod process could record
                    # exits (SIGKILL path): reconcile against the
                    # supervisor's view, like readServiceFile cross-
                    # checking systemd state (rkt.go:890)
                    exit_code = 137
                else:
                    exit_code = int(app.get("exit_code") or 0)
                rp.containers.append(RuntimeContainer(
                    id=f"{rec['uuid']}:{app_name}", name=app_name,
                    image=app.get("image", ""),
                    state=(ContainerState.RUNNING if running
                           else ContainerState.EXITED),
                    started_at=float(app.get("started_at") or 0.0),
                    finished_at=float(app.get("finished_at") or 0.0),
                    exit_code=exit_code,
                    restart_count=rec["attempt"]))
            pods.append(rp)
        return pods

    def _make_manifest(self, pod: api.Pod) -> dict:
        """Appc-style pod manifest from the spec (ref: makePodManifest
        rkt.go:424 + setApp :335 — exec is command+args, environment is
        name/value pairs; kubernetes identity rides annotations)."""
        apps = []
        for c in pod.spec.containers:
            apps.append({
                "name": c.name,
                "image": c.image,
                "app": {
                    "exec": list(c.command) + list(c.args),
                    "environment": [{"name": e.name, "value": e.value}
                                    for e in c.env],
                },
            })
        return {
            "acVersion": "0.7.4",
            "acKind": "PodManifest",
            "apps": apps,
            "annotations": [
                {"name": "k8s.io/pod-uid", "value": pod.metadata.uid},
                {"name": "k8s.io/pod-name", "value": pod.metadata.name},
                {"name": "k8s.io/pod-namespace",
                 "value": pod.metadata.namespace},
            ],
        }

    def start_container(self, pod: api.Pod, container: api.Container
                        ) -> RuntimeContainer:
        """Pod-granular start: if this container's app is already
        running in the current pod generation, this is a no-op (the
        generation launched it); otherwise the WHOLE pod restarts as a
        new generation (ref: SyncPod rkt.go:1156-1219 — any restartable
        container change -> restartPod)."""
        uid = pod.metadata.uid
        rec = self._record_for(uid)
        if rec is not None:
            status = self._status(rec["uuid"], fresh=True) or {"apps": {}}
            app = status.get("apps", {}).get(container.name)
            if (app is not None and app.get("state") == "running"
                    and self.units.unit_state(rec["unit"]) == ACTIVE):
                return RuntimeContainer(
                    id=f"{rec['uuid']}:{container.name}",
                    name=container.name, image=container.image,
                    state=ContainerState.RUNNING,
                    restart_count=rec["attempt"])
            if (app is not None and app.get("state") == "exited"
                    and not _should_restart(
                        pod.spec.restart_policy,
                        int(app.get("exit_code") or 0))):
                # the app already ran in this generation and the policy
                # forbids another run; a whole-pod restart here (e.g.
                # for a sibling that raced to completion before the
                # kubelet's first snapshot) would re-execute it
                return RuntimeContainer(
                    id=f"{rec['uuid']}:{container.name}",
                    name=container.name, image=container.image,
                    state=ContainerState.EXITED,
                    exit_code=int(app.get("exit_code") or 0),
                    restart_count=rec["attempt"])
        attempt = rec["attempt"] + 1 if rec is not None else 0
        unit = unit_name_for(uid)
        if rec is not None:
            self.units.stop_unit(unit)
            # the superseded generation's prepared data is dead weight
            # the moment a new uuid takes the unit over (logs live in
            # the unit journal, status in the new uuid): collect it now
            # rather than leaving it for a global sweep — a global
            # `gc` could reap KEPT corpses and pods mid-prepare
            if rec["uuid"]:
                self._forget_status(rec["uuid"])
                self._gc_uuid(rec["uuid"])
        uuid = self._run("prepare", "--stdin-manifest",
                         input_text=json.dumps(
                             self._make_manifest(pod))).strip()
        exec_start = " ".join(
            shlex.quote(a) for a in self.cli + ["run-prepared", uuid])
        self.units.write_unit(unit, [
            ("Unit", "Description",
             f"{pod.metadata.namespace}/{pod.metadata.name}"),
            ("Service", "ExecStart", exec_start),
            (K8S_SECTION, "POD_UID", uid),
            (K8S_SECTION, "POD_NAME", pod.metadata.name),
            (K8S_SECTION, "POD_NAMESPACE", pod.metadata.namespace),
            (K8S_SECTION, "PREPARED_UUID", uuid),
            (K8S_SECTION, "ATTEMPT", str(attempt)),
        ])
        self.units.restart_unit(unit)
        # the pod process records every app "running" at launch; wait
        # for that first status so same-sync start_container calls for
        # sibling containers see the new generation (RunPod returns
        # only after systemd starts the unit, rkt.go:774-806)
        deadline = time.time() + 10.0
        while time.time() < deadline:
            status = self._status(uuid, fresh=True)
            if status and status.get("apps"):
                break
            if self.units.unit_state(unit) != ACTIVE:
                # the unit may have run to COMPLETION between polls (a
                # fast one-shot pod): its final status still counts as
                # started; only a statusless death is a start failure
                status = self._status(uuid, fresh=True)
                if status and status.get("apps"):
                    break
                raise CliError(f"pod unit {unit} died at start: "
                               f"{self.units.journal(unit, 5)!r}")
            time.sleep(0.02)
        return RuntimeContainer(
            id=f"{uuid}:{container.name}", name=container.name,
            image=container.image, state=ContainerState.RUNNING,
            restart_count=attempt)

    def kill_container(self, pod_uid: str, name: str) -> None:
        """No per-app kill exists at this boundary: stop the whole unit
        and let the restart policy revive the pod (ref: rkt.go:982
        KillPod; SyncPod's whole-pod restart on liveness failure)."""
        self.kill_pod(pod_uid, remove=False)

    def kill_pod(self, pod_uid: str, remove: bool = True,
                 grace_seconds: Optional[float] = None) -> None:
        """Stop the unit; with remove=True also drop the unit file and
        prepared-pod data (the Runtime contract here folds the GC's
        removal in, like daemon_runtime.kill_pod). remove=False keeps
        the corpse for logs/status and touches the service file so the
        min-age GC defers (rkt.go:991-999). grace_seconds is accepted
        for the Runtime contract; the unit manager's stop is already
        systemd-style graceful with its own timeout."""
        unit = unit_name_for(pod_uid)
        if not self.units.has_unit(unit):
            return
        rec = self._record(unit)
        self.units.stop_unit(unit)
        if rec and rec["uuid"]:
            self._forget_status(rec["uuid"])
        if not remove:
            self.units.touch(unit)
            return
        # gc the prepared data BEFORE dropping the unit record: the
        # unit file is the only pointer to the uuid, so a failed gc
        # after removal would leak the pod directory unreachably (the
        # orphan set backstops a transient failure either way)
        if rec and rec["uuid"]:
            self._gc_uuid(rec["uuid"])
        self.units.remove_unit(unit)

    def get_container_logs(self, pod_uid: str, name: str,
                           tail_lines: int = 0,
                           previous: bool = False) -> str:
        """Logs ride the unit journal; the pod process tags each line
        with its app name, so per-container logs are a journal filter
        (ref: GetContainerLogs -> journalctl -u <unit>)."""
        if previous:
            raise KeyError('unit journals keep no previous generation')
        rec = self._record_for(pod_uid)
        if rec is None:
            raise KeyError(f"pod {pod_uid!r} not found")
        status = self._status(rec["uuid"]) or {"apps": {}}
        if name not in status.get("apps", {}):
            raise KeyError(f"container {name!r} not found")
        prefix = f"{name}: "
        lines = [ln[len(prefix):] + "\n"
                 for ln in self.units.journal(rec["unit"]).splitlines()
                 if ln.startswith(prefix)]
        return tail_text("".join(lines), tail_lines)

    def exec_in_container(self, pod_uid: str, name: str,
                          cmd: List[str]) -> Tuple[int, str]:
        """(ref: ExecInContainer -> `rkt enter --app=<name> <uuid>`)"""
        rec = self._record_for(pod_uid)
        if rec is None:
            raise KeyError(f"pod {pod_uid!r} not found")
        status = self._status(rec["uuid"], fresh=True) or {"apps": {}}
        app = status.get("apps", {}).get(name)
        if app is None or app.get("state") != "running":
            raise KeyError(f"container {name!r} not running")
        try:
            proc = subprocess.run(
                self.cli + ["enter", f"--app={name}", rec["uuid"], "--"]
                + list(cmd),
                capture_output=True, text=True, timeout=self.cli_timeout,
                stdin=subprocess.DEVNULL)
        except subprocess.TimeoutExpired:
            # same convention as subprocess_runtime: timeout is exit
            # 124 + message, never a raw exception into the server
            return 124, f"exec timed out after {self.cli_timeout}s"
        return proc.returncode, proc.stdout + proc.stderr

    def pull_image(self, image: str, keyring=None) -> None:
        """(ref: PullImage rkt.go:1093 — `rkt fetch`). Registry
        credentials are not flags: the reference writes them into the
        CLI's auth config dir before fetching (writeDockerAuthConfig
        rkt.go:1049, the /etc/rkt/auth.d shape); this adapter does the
        same into its auth_dir so imagePullSecrets actually reach the
        fetch."""
        if keyring is not None:
            creds = keyring.lookup(image)
            if creds:
                from .credentialprovider import image_registry
                # most specific credential wins (keyring order)
                self._write_auth_config(image_registry(image), creds[0])
        self._run("fetch", image)

    def _write_auth_config(self, registry: str, cred) -> None:
        """One dockerAuth config file per registry (rkt.go:1049-1091
        writes {rktKind: dockerAuth, registries, credentials})."""
        os.makedirs(self.auth_dir, mode=0o700, exist_ok=True)
        path = os.path.join(self.auth_dir,
                            f"{registry.replace('/', '_')}.json")
        tmp = path + ".tmp"
        # plaintext registry password: owner-only, like /etc/rkt/auth.d
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump({
                "rktKind": "dockerAuth",
                "rktVersion": "v1",
                "registries": [registry],
                "credentials": {"user": cred.username,
                                "password": cred.password},
            }, f)
        os.replace(tmp, path)

    def pod_port_address(self, pod_uid: str, port: int) -> Tuple[str, int]:
        """Pods at this boundary run host-networked (the fake CLI's
        apps are host processes), so ports are loopback-reachable."""
        return ("127.0.0.1", port)

    # --------------------------------------------------------------- GC

    def garbage_collect(self, keep_uids: Iterable[str] = (),
                        min_age_seconds: float = 60.0) -> int:
        """(ref: GarbageCollect rkt.go:1221-1260 — reset failed units,
        remove inactive service files, gc the removed units' prepared
        pods; superseded generations are collected at replacement time
        in start_container, so no global sweep is needed.)
        keep_uids guards pods the kubelet still desires: the reference
        swept every inactive unit, which could re-trigger a completed
        pod's start under a restart-from-missing sync — the desired-set
        guard closes that hole while keeping the sweep shape. The
        min-age gate reads the service-file mtime the stop path
        touches (rkt.go:991)."""
        keep = set(keep_uids)
        removed = 0
        self.units.reset_failed()
        for uuid in list(self._orphan_uuids):
            self._gc_uuid(uuid)  # retry transiently-failed collections
        for rec in self._records():
            if rec["uid"] in keep:
                continue
            unit = rec["unit"]
            if self.units.unit_state(unit) == ACTIVE:
                continue
            if self.units.unit_age(unit) < min_age_seconds:
                continue
            if rec["uuid"]:
                self._forget_status(rec["uuid"])
                self._gc_uuid(rec["uuid"])
            self.units.remove_unit(unit)
            removed += 1
        return removed

    def _gc_uuid(self, uuid: str) -> None:
        """Collect one prepared pod; a failure parks the uuid in the
        orphan set for the next sweep instead of leaking it."""
        try:
            self._run("gc", "--uuid", uuid)
        except CliError:
            self._orphan_uuids.add(uuid)
        else:
            self._orphan_uuids.discard(uuid)
