"""Pod bandwidth shaping.

The reference kubelet reads the ``kubernetes.io/ingress-bandwidth`` /
``kubernetes.io/egress-bandwidth`` pod annotations and programs an HTB
queueing discipline through the ``tc`` tool (ref:
pkg/util/bandwidth/linux.go tcShaper — per-CIDR u32 filters into
per-rate classes under the ``1:`` root; pkg/kubelet/kubelet.go:3287
validateBandwidthIsReasonable, :3297 extractBandwidthResources,
:1730 syncNetworkStatus reconcile + :1826 cleanupBandwidthLimits).

The tc implementation here speaks the same command surface through an
injectable runner (the reference injects exec.Interface and tests
against canned outputs, linux_test.go) — a real ``tc`` binary works
unchanged; tests use a fake runner. A recording FakeShaper plays the
fake_shaper.go role for kubelet-level tests.
"""

from __future__ import annotations

import ipaddress
import subprocess
from typing import Callable, Dict, List, Optional, Tuple

from ..core import types as api
from ..core.quantity import Quantity, parse_quantity

INGRESS_ANNOTATION = "kubernetes.io/ingress-bandwidth"
EGRESS_ANNOTATION = "kubernetes.io/egress-bandwidth"

_MIN_BPS = 1_000                  # 1kbit (kubelet.go:3285 minRsrc)
_MAX_BPS = 1_000_000_000_000_000  # 1Pbit (maxRsrc)


def _validate(q: Quantity, which: str) -> None:
    if q.value < _MIN_BPS:
        raise ValueError(f"{which} bandwidth is unreasonably small "
                         f"(< 1kbit)")
    if q.value > _MAX_BPS:
        raise ValueError(f"{which} bandwidth is unreasonably large "
                         f"(> 1Pbit)")


def extract_pod_bandwidth(pod: api.Pod
                          ) -> Tuple[Optional[Quantity],
                                     Optional[Quantity]]:
    """(ingress, egress) from the pod's annotations, validated
    (kubelet.go:3297 extractBandwidthResources)."""
    ingress = egress = None
    raw = pod.metadata.annotations.get(INGRESS_ANNOTATION)
    if raw:
        ingress = parse_quantity(raw)
        _validate(ingress, "ingress")
    raw = pod.metadata.annotations.get(EGRESS_ANNOTATION)
    if raw:
        egress = parse_quantity(raw)
        _validate(egress, "egress")
    return ingress, egress


class Shaper:
    """(interfaces.go BandwidthShaper)"""

    def reconcile_interface(self) -> None:
        """Ensure the root queueing discipline exists."""
        raise NotImplementedError

    def reconcile_cidr(self, cidr: str, egress: Optional[Quantity],
                       ingress: Optional[Quantity]) -> None:
        raise NotImplementedError

    def get_cidrs(self) -> List[str]:
        raise NotImplementedError

    def reset(self, cidr: str) -> None:
        raise NotImplementedError


class FakeShaper(Shaper):
    """(fake_shaper.go) — records calls, serves canned CIDRs."""

    def __init__(self):
        self.limits: Dict[str, Tuple[Optional[Quantity],
                                     Optional[Quantity]]] = {}
        self.resets: List[str] = []

    def reconcile_interface(self) -> None:
        pass

    def reconcile_cidr(self, cidr, egress, ingress) -> None:
        self.limits[cidr] = (egress, ingress)

    def get_cidrs(self) -> List[str]:
        return sorted(self.limits)

    def reset(self, cidr: str) -> None:
        self.resets.append(cidr)
        self.limits.pop(cidr, None)


def hex_cidr(cidr: str) -> str:
    """Text CIDR -> tc's hex match form, masked (linux.go hexCIDR:
    1.2.3.4/16 -> hex(1.2.0.0)/ffff0000)."""
    net = ipaddress.ip_network(cidr, strict=False)
    return (net.network_address.packed.hex()
            + "/" + net.netmask.packed.hex())


def ascii_cidr(hexed: str) -> str:
    """The opposite (linux.go asciiCIDR)."""
    ip_part, _, mask_part = hexed.partition("/")
    ip = ipaddress.ip_address(bytes.fromhex(ip_part))
    prefix = bin(int(mask_part, 16)).count("1")
    return f"{ip}/{prefix}"


def _default_runner(args: List[str]) -> str:
    out = subprocess.run(args, capture_output=True, text=True,
                         timeout=30.0)
    if out.returncode != 0:
        raise RuntimeError(f"{' '.join(args)}: rc={out.returncode} "
                           f"{out.stdout}{out.stderr}".strip())
    return out.stdout


class TCShaper(Shaper):
    """HTB shaping via tc (linux.go tcShaper). runner executes one
    command argv and returns stdout, raising on nonzero exit."""

    def __init__(self, iface: str,
                 runner: Optional[Callable[[List[str]], str]] = None):
        self.iface = iface
        self._run = runner or _default_runner

    def reconcile_interface(self) -> None:
        # (linux.go ReconcileInterface: add the root htb qdisc once)
        out = self._run(["tc", "qdisc", "show", "dev", self.iface])
        if "htb 1:" in out:
            return
        self._run(["tc", "qdisc", "add", "dev", self.iface, "root",
                   "handle", "1:", "htb", "default", "30"])

    def _next_class_id(self) -> int:
        # (linux.go nextClassID: first free 1:N)
        out = self._run(["tc", "class", "show", "dev", self.iface])
        used = set()
        for line in out.splitlines():
            parts = line.split()
            # class htb 1:1 root prio 0 rate 1000Kbit ...
            if len(parts) >= 3 and parts[0] == "class":
                used.add(parts[2])
        n = 1
        while f"1:{n}" in used:
            n += 1
        return n

    def _make_class(self, rate_kbit: str) -> int:
        cls = self._next_class_id()
        self._run(["tc", "class", "add", "dev", self.iface,
                   "parent", "1:", "classid", f"1:{cls}",
                   "htb", "rate", rate_kbit])
        return cls

    @staticmethod
    def _kbit(q: Quantity) -> str:
        return f"{q.value // 1000}kbit"  # (linux.go makeKBitString)

    @staticmethod
    def _rate_bps(rate: str) -> int:
        """tc normalizes display units (input '10000kbit' shows as
        '10Mbit'); compare rates numerically, not textually."""
        r = rate.strip().lower()
        for suffix, mult in (("gbit", 10 ** 9), ("mbit", 10 ** 6),
                             ("kbit", 10 ** 3), ("bit", 1)):
            if r.endswith(suffix):
                try:
                    return int(float(r[:-len(suffix)]) * mult)
                except ValueError:
                    return -1
        return -1

    # u32 match offsets in the IP header: dst at 16, src at 12
    _OFFSET = {"dst": "16", "src": "12"}

    def _find_cidr_filter(self, cidr: str, direction: str
                          ) -> Optional[Tuple[str, str]]:
        """(flowid, filter handle) of the u32 filter matching the CIDR
        in one direction (linux.go findCIDRClass, made per-direction so
        a partially-programmed pod can be completed)."""
        out = self._run(["tc", "filter", "show", "dev", self.iface])
        spec = f"match {hex_cidr(cidr)} at {self._OFFSET[direction]}"
        header: List[str] = []
        for line in out.splitlines():
            line = line.strip()
            if line.startswith("filter"):
                header = line.split()
                continue
            if spec in line and header:
                # filter parent 1: protocol ip pref 1 u32 fh 800::800
                # order 2048 key ht 800 bkt 0 flowid 1:1
                fh = header[header.index("fh") + 1] \
                    if "fh" in header else ""
                flow = header[header.index("flowid") + 1] \
                    if "flowid" in header else ""
                return flow, fh
        return None

    def _class_rates(self) -> Dict[str, str]:
        out = self._run(["tc", "class", "show", "dev", self.iface])
        rates = {}
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 3 and parts[0] == "class" \
                    and "rate" in parts:
                rates[parts[2]] = parts[parts.index("rate") + 1]
        return rates

    def _del_filter(self, flow: str, fh: str) -> None:
        self._run(["tc", "filter", "del", "dev", self.iface,
                   "parent", "1:", "proto", "ip", "prio", "1",
                   "handle", fh, "u32"])
        self._run(["tc", "class", "del", "dev", self.iface,
                   "parent", "1:", "classid", flow])

    def reconcile_cidr(self, cidr, egress, ingress) -> None:
        """Each direction idempotent on its own, and a changed
        annotation reprograms the class (the reference's ReconcileCIDR
        early-returns on any existing filter, which strands the second
        direction after a partial failure and never applies rate
        edits)."""
        # ingress = traffic TO the pod (match dst); egress = FROM (src)
        for want, direction in ((ingress, "dst"), (egress, "src")):
            existing = self._find_cidr_filter(cidr, direction)
            if want is None:
                if existing is not None:
                    # annotation removed: drop the stale direction
                    self._del_filter(*existing)
                continue
            rate = self._kbit(want)
            if existing is not None:
                flow, fh = existing
                current = self._rate_bps(
                    self._class_rates().get(flow, ""))
                if current == self._rate_bps(rate):
                    continue  # already programmed at this rate
                self._del_filter(flow, fh)
            cls = self._make_class(rate)
            self._run(["tc", "filter", "add", "dev", self.iface,
                       "protocol", "ip", "parent", "1:0", "prio", "1",
                       "u32", "match", "ip", direction, cidr,
                       "flowid", f"1:{cls}"])

    def get_cidrs(self) -> List[str]:
        # (linux.go GetCIDRs: every u32 match in the filter table)
        out = self._run(["tc", "filter", "show", "dev", self.iface])
        cidrs = []
        for line in out.splitlines():
            line = line.strip()
            if line.startswith("match"):
                cidrs.append(ascii_cidr(line.split()[1]))
        return sorted(set(cidrs))

    def reset(self, cidr: str) -> None:
        # (linux.go Reset: delete the filter(s) and their classes —
        # both directions)
        for direction in ("dst", "src"):
            found = self._find_cidr_filter(cidr, direction)
            if found is not None:
                self._del_filter(*found)
