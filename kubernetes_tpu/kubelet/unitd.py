"""Unit manager — the systemd role the CLI-shaped runtime drives.

Reference: pkg/kubelet/rkt/rkt.go — the rkt runtime never supervises
processes itself; it writes a systemd service file per pod
(preparePod rkt.go:626-729, unit options built via go-systemd's
newUnitOption rkt.go:592) and then drives systemd over dbus:
RestartUnit with the "replace" mode (rkt.go:806), StopUnit
(rkt.go:1000), ListUnits + ResetFailed during GarbageCollect
(rkt.go:1221-1260), and reads the unit's journal for logs
(journalctl -u role). This module is that supervisor boundary for the
TPU-native kubelet: units are INI files in a directory, ExecStart is
spawned as a real OS process group, and the unit's combined
stdout/stderr is its journal file.

The unit FILE's mtime is load-bearing exactly as in the reference:
KillPod touches the service file so a freshly stopped pod is not
immediately garbage-collected (rkt.go:991-999); the GC's min-age check
reads it back.
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from .container import tail_text

UnitOption = Tuple[str, str, str]  # (section, key, value)

ACTIVE = "active"        # ExecStart process is running
INACTIVE = "inactive"    # never started here, or exited 0, or reset
FAILED = "failed"        # exited nonzero / killed


def _proc_start_time(pid: int) -> str:
    """/proc starttime (field 22) — a (pid, starttime) pair survives
    PID recycling; a bare pid does not."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rpartition(")")[2].split()[19]
    except (OSError, IndexError):
        return ""


def _pgroup_alive(pid: int) -> bool:
    """True while the process group leader is a live (non-zombie)
    process. killpg(pid, 0) alone is not enough: an exited-but-unreaped
    leader (possible when adopter and spawner share a process, as in
    tests) still accepts signal 0; /proc state distinguishes it."""
    try:
        os.killpg(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            # field 3 (after the parenthesized comm, which may contain
            # spaces) is the state letter
            state = f.read().rpartition(")")[2].split()[0]
        return state != "Z"
    except (OSError, IndexError):
        return True  # no /proc: trust the signal probe


class UnitManager:
    """Filesystem-backed unit supervisor (the systemdInterface seam)."""

    def __init__(self, unit_dir: str):
        os.makedirs(unit_dir, exist_ok=True)
        self.unit_dir = unit_dir
        self._procs: Dict[str, subprocess.Popen] = {}
        self._start_times: Dict[str, str] = {}  # name -> leader starttime
        self._lock = threading.Lock()

    # ------------------------------------------------------- adoption

    def _pid_path(self, name: str) -> str:
        return self._path(name) + ".pid"

    def _adopted_pid(self, name: str) -> Optional[int]:
        """A live process group from a PREVIOUS manager (the systemd
        property the reference leans on: units outlive the kubelet, and
        a restarted kubelet re-attaches instead of double-launching).
        The pid rides a pidfile next to the unit; liveness is probed
        with signal 0 against the process group."""
        with self._lock:
            if name in self._procs:
                return None  # tracked in-process, not adopted
        try:
            with open(self._pid_path(name)) as f:
                fields = f.read().split()
                pid = int(fields[0])
                start_time = fields[1] if len(fields) > 1 else ""
        except (OSError, ValueError, IndexError):
            return None
        if not _pgroup_alive(pid):
            return None
        # identity check: a recycled pid must not be adopted (or
        # killed) as if it were the unit (start-time pairing). An
        # EMPTY observed start time with a live group means the leader
        # died but group members survive — the pgid cannot have been
        # recycled while the group lives, so it is still ours and must
        # remain adoptable (else leader-crash orphans leak forever).
        observed = _proc_start_time(pid)
        if start_time and observed and observed != start_time:
            return None
        return pid

    # ------------------------------------------------------- unit files

    def _path(self, name: str) -> str:
        return os.path.join(self.unit_dir, name)

    def _journal_path(self, name: str) -> str:
        return self._path(name) + ".journal"

    def write_unit(self, name: str, options: List[UnitOption]) -> None:
        """Serialize ordered unit options into an INI-style service file
        (ref: unit.Serialize over newUnitOption lists, rkt.go:684-701).
        Atomic: a reader never sees a half-written unit."""
        lines: List[str] = []
        current: Optional[str] = None
        for section, key, value in options:
            if section != current:
                if lines:
                    lines.append("")
                lines.append(f"[{section}]")
                current = section
            lines.append(f"{key}={value}")
        tmp = self._path(name) + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines) + "\n")
        os.replace(tmp, self._path(name))

    def read_unit(self, name: str) -> List[UnitOption]:
        """Parse a service file back into ordered (section, key, value)
        options (ref: readServiceFile rkt.go:890-935)."""
        options: List[UnitOption] = []
        section = ""
        with open(self._path(name)) as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                if line.startswith("[") and line.endswith("]"):
                    section = line[1:-1]
                    continue
                key, _, value = line.partition("=")
                options.append((section, key, value))
        return options

    def unit_option(self, name: str, section: str, key: str,
                    default: Optional[str] = None) -> Optional[str]:
        for sec, k, v in self.read_unit(name):
            if sec == section and k == key:
                return v
        return default

    def unit_names(self) -> List[str]:
        return sorted(f for f in os.listdir(self.unit_dir)
                      if f.endswith(".service"))

    def has_unit(self, name: str) -> bool:
        return os.path.exists(self._path(name))

    def unit_age(self, name: str) -> float:
        return time.time() - os.path.getmtime(self._path(name))

    def touch(self, name: str) -> None:
        """Update the service file's mtime — the reference's trick for
        deferring GC of a just-stopped pod (rkt.go:991-999)."""
        os.utime(self._path(name), None)

    # -------------------------------------------------------- lifecycle

    def restart_unit(self, name: str) -> None:
        """'replace' semantics (rkt.go:806 RestartUnit(name, "replace")):
        stop whatever instance is running, then start a fresh one from
        the CURRENT service file's ExecStart."""
        self.stop_unit(name)
        exec_start = self.unit_option(name, "Service", "ExecStart")
        if not exec_start:
            raise ValueError(f"unit {name!r} has no ExecStart")
        argv = shlex.split(exec_start)
        journal = open(self._journal_path(name), "ab")
        try:
            proc = subprocess.Popen(
                argv, stdout=journal, stderr=subprocess.STDOUT,
                stdin=subprocess.DEVNULL, start_new_session=True)
        finally:
            journal.close()  # the child owns the descriptor now
        leader_start = _proc_start_time(proc.pid)
        with open(self._pid_path(name), "w") as f:
            f.write(f"{proc.pid} {leader_start}")
        with self._lock:
            self._procs[name] = proc
            self._start_times[name] = leader_start

    def stop_unit(self, name: str, grace: float = 5.0) -> None:
        """SIGTERM the unit's process group, escalate to SIGKILL after
        the grace period (systemd's default stop behavior; the rkt pod
        process forwards the signal to its apps)."""
        with self._lock:
            proc = self._procs.get(name)
        if proc is not None:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
                try:
                    proc.wait(timeout=grace)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
                    proc.wait()
            # the leader may be gone while group members survive (a
            # crashed pod process leaves its apps behind): sweep the
            # group before declaring the unit stopped. Identity-guard
            # it: if /proc shows a DIFFERENT process now owning the
            # pid, our group is fully gone and the pid was recycled —
            # killing it would hit an innocent process group. (A live
            # group pins its pgid against recycling, so an empty or
            # matching observation is safely ours.)
            with self._lock:
                recorded = self._start_times.get(name, "")
            observed = _proc_start_time(proc.pid)
            if recorded and observed and observed != recorded:
                return
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            return
        # adopted process group from a previous manager instance
        pid = self._adopted_pid(name)
        if pid is None:
            return
        try:
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.time() + grace
        while time.time() < deadline:
            if not _pgroup_alive(pid):
                return
            time.sleep(0.05)
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def unit_state(self, name: str) -> str:
        """active | inactive | failed, from the supervised process.
        A unit started by a PREVIOUS manager whose process group still
        lives reads ACTIVE via its pidfile (systemd's re-attach
        property); one that is gone reads inactive — the state the
        reference's GC sweeps (rkt.go:1230-1243)."""
        with self._lock:
            proc = self._procs.get(name)
        if proc is None:
            return ACTIVE if self._adopted_pid(name) is not None \
                else INACTIVE
        rc = proc.poll()
        if rc is None:
            return ACTIVE
        return INACTIVE if rc == 0 else FAILED

    def list_units(self) -> Dict[str, str]:
        """(ref: systemd ListUnits, rkt.go:1231)"""
        return {name: self.unit_state(name) for name in self.unit_names()}

    def reset_failed(self) -> None:
        """Clear failed-state records (systemctl reset-failed; the
        reference calls it first thing in GarbageCollect, rkt.go:1222)."""
        with self._lock:
            for name in list(self._procs):
                proc = self._procs[name]
                rc = proc.poll()
                if rc is not None and rc != 0:
                    del self._procs[name]

    def remove_unit(self, name: str) -> None:
        """Stop + delete the service file and its journal
        (ref: GC's os.Remove of inactive service files, rkt.go:1250-1253)."""
        self.stop_unit(name)
        with self._lock:
            self._procs.pop(name, None)
            self._start_times.pop(name, None)
        for path in (self._path(name), self._journal_path(name),
                     self._pid_path(name)):
            try:
                os.remove(path)
            except FileNotFoundError:
                pass

    # ---------------------------------------------------------- journal

    def journal(self, name: str, tail_lines: int = 0) -> str:
        """The unit's captured stdout/stderr (journalctl -u role — the
        reference reads pod logs straight from the journal because the
        pod's apps write there, rkt.go GetContainerLogs)."""
        try:
            with open(self._journal_path(name), "rb") as f:
                text = f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""
        return tail_text(text, tail_lines)
