"""Master composition — one config in, a fully wired API server out.

Reference: pkg/master/master.go:279 (Master struct; resource map :575-610,
handler chain auth->authz->apis :702,710) as driven by
cmd/kube-apiserver/app/server.go:358 (APIServer.Run: admission chain
built :516-517 from the --admission-control list, auth plugins from
flags). The registry's per-resource strategies and both API groups are
installed by Registry/ApiServer themselves; this module is the one place
that composes store + admission + authn/authz + server, instead of every
caller hand-assembling them (the round-1 gap: composition lived ad-hoc
in tests and benchmarks).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .admission import registry_hook
from .admission.plugins import new_from_plugins
from .api.registry import Registry
from .api.server import ApiServer
from .auth.authenticate import (Authenticator, BasicAuthAuthenticator,
                                TokenAuthenticator, UnionAuthenticator)
from .auth.authorize import (AlwaysAllowAuthorizer, AlwaysDenyAuthorizer,
                             abac_from_lines)
from .core.errors import BadRequest


def _healthz_probe(port: int, host: str = "127.0.0.1"):
    def probe():
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=2) as resp:
                body = resp.read().decode(errors="replace").strip()
                if resp.status == 200:
                    return True, body or "ok"
                return False, f"healthz status {resp.status}: {body}"
        except Exception as e:
            return False, f"Get http://{host}:{port}/healthz: {e}"
    return probe


@dataclass
class MasterConfig:
    """(ref: master.go:157 Config + the cmd/kube-apiserver flag surface)"""
    host: str = "127.0.0.1"
    port: int = 0                      # 0 = ephemeral (tests)
    # "memory" = pure-Python Store; "native" = the C++ KV engine
    # (core/native_store.py — the external-store cost profile)
    storage_backend: str = "memory"
    # ref: --admission-control (server.go:230); plugin names as registered
    # in admission/plugins.py
    admission_control: List[str] = field(default_factory=list)
    # authn: htpasswd-style "password,user,uid" lines / token lines
    # (ref: plugin/pkg/auth/authenticator password/passwordfile, tokenfile)
    basic_auth_lines: Optional[List[str]] = None
    token_auth_lines: Optional[List[str]] = None
    # OIDC (ref: --oidc-issuer-url/--oidc-client-id, oidc.go): RS256
    # verified against a JWKS document (pure-Python PKCS#1 v1.5,
    # auth/rsa.py); oidc_hs256_secret adds the local-IdP HS256 mode
    oidc_jwks: Optional[dict] = None
    oidc_issuer: str = ""
    oidc_client_id: str = ""
    oidc_username_claim: str = "sub"
    oidc_groups_claim: str = "groups"
    oidc_hs256_secret: Optional[bytes] = None
    # ref: --experimental-keystone-url (keystone.go): basic-auth
    # delegated to a keystone-v2-shaped endpoint
    keystone_url: str = ""
    # ref: master.go tunneler wiring (--ssh-user/--ssh-keyfile enable
    # the SSH tunneler there): master->node traffic rides maintained
    # tunnels, with a healthz gate on tunnel-sync age
    enable_tunneler: bool = False
    # authz: AlwaysAllow | AlwaysDeny | ABAC (ref: --authorization-mode)
    authorization_mode: str = "AlwaysAllow"
    authorization_policy_lines: Optional[List[str]] = None
    service_cidr: str = "10.0.0.0/24"  # ref: --service-cluster-ip-range
    max_in_flight: int = 400           # ref: --max-requests-inflight
    # secure serving (ref: --tls-cert-file/--tls-private-key-file); with
    # a client CA, x509 client-cert auth joins the authenticator union
    # (ref: --client-ca-file)
    tls_cert_file: str = ""
    tls_key_file: str = ""
    tls_client_ca_file: str = ""
    # ref: --runtime-config (server.go:244): group-version / per-resource
    # on-off switches, e.g. {"apis/extensions/v1beta1": False} or
    # {"apis/extensions/v1beta1/jobs": False}; "api/all" covers every
    # version
    runtime_config: Optional[Dict[str, bool]] = None


class Master:
    """Composed control-plane head: store + registry + admission + auth +
    HTTP server. start() serves; InProcClient(master.registry) gives
    co-resident components the zero-copy path (the reference's equivalent
    is compiling into one binary next to master.New)."""

    def __init__(self, config: Optional[MasterConfig] = None):
        self.config = config or MasterConfig()
        cfg = self.config

        if cfg.storage_backend == "native":
            from .core.native_store import NativeStore
            self.store = NativeStore()
        elif cfg.storage_backend == "memory":
            self.store = None  # Registry builds its own Store
        else:
            raise BadRequest(
                f"unknown storage backend {cfg.storage_backend!r}")

        self.registry = Registry(store=self.store,
                                 service_cidr=cfg.service_cidr)
        if cfg.admission_control:
            self.registry.admission = registry_hook(
                new_from_plugins(self.registry, cfg.admission_control))

        authenticators: List[Authenticator] = []
        if cfg.tls_client_ca_file:
            from .auth.authenticate import X509Authenticator
            authenticators.append(X509Authenticator())
        if cfg.basic_auth_lines:
            authenticators.append(
                BasicAuthAuthenticator.from_lines(cfg.basic_auth_lines))
        if cfg.token_auth_lines:
            authenticators.append(
                TokenAuthenticator.from_lines(cfg.token_auth_lines))
        if cfg.keystone_url:
            from .auth.authenticate import KeystonePasswordAuthenticator
            authenticators.append(
                KeystonePasswordAuthenticator(cfg.keystone_url))
        if cfg.oidc_jwks or cfg.oidc_hs256_secret:
            from .auth.authenticate import JWTAuthenticator
            authenticators.append(JWTAuthenticator(
                secret=cfg.oidc_hs256_secret, jwks=cfg.oidc_jwks,
                issuer=cfg.oidc_issuer, audience=cfg.oidc_client_id,
                username_claim=cfg.oidc_username_claim,
                groups_claim=cfg.oidc_groups_claim))
        if not authenticators:
            authenticator = None
        elif len(authenticators) == 1:
            authenticator = authenticators[0]
        else:
            authenticator = UnionAuthenticator(authenticators)

        mode = cfg.authorization_mode
        if mode == "AlwaysAllow":
            authorizer = AlwaysAllowAuthorizer()
        elif mode == "AlwaysDeny":
            authorizer = AlwaysDenyAuthorizer()
        elif mode == "ABAC":
            authorizer = abac_from_lines(cfg.authorization_policy_lines or [])
        else:
            raise BadRequest(f"unknown authorization mode {mode!r}")

        self.server = ApiServer(self.registry, host=cfg.host, port=cfg.port,
                                max_in_flight=cfg.max_in_flight,
                                authenticator=authenticator,
                                authorizer=authorizer,
                                tls_cert_file=cfg.tls_cert_file,
                                tls_key_file=cfg.tls_key_file,
                                tls_client_ca_file=cfg.tls_client_ca_file,
                                runtime_config=cfg.runtime_config)

        # componentstatus probes at the components' conventional healthz
        # ports (ref: master.go getServersToValidate: scheduler :10251,
        # controller-manager :10252)
        from .utils.healthz import (CONTROLLER_MANAGER_PORT,
                                    SCHEDULER_PORT)
        self.registry.add_component_probe(
            "scheduler", _healthz_probe(SCHEDULER_PORT))
        self.registry.add_component_probe(
            "controller-manager", _healthz_probe(CONTROLLER_MANAGER_PORT))

        self.tunneler = None
        if cfg.enable_tunneler:
            from .api.relay import kubelet_base_for
            from .api.tunneler import WsTunneler

            def node_addresses():
                import urllib.parse as _up
                out = []
                nodes, _rev = self.registry.list("nodes", "")
                for node in nodes:
                    try:
                        base = kubelet_base_for(self.registry,
                                                node.metadata.name)
                    except Exception:
                        continue
                    split = _up.urlsplit(base)
                    if split.hostname and split.port:
                        out.append((node.metadata.name, split.hostname,
                                    split.port))
                return out

            self.tunneler = WsTunneler()
            self.tunneler.run(node_addresses)
            # node-proxy GETs ride the tunnels (master.go wires
            # tunneler.Dial into the proxy transport the same way)
            self.server.tunnel_dial = self.tunneler.dial
            # the tunnel-sync healthz gate (ref: master.go
            # IsTunnelSyncHealthy wired into apiserver healthz)
            self.registry.add_component_probe(
                "tunneler",
                lambda: ((True, "ok") if self.tunneler.healthy()
                         else (False,
                               f"tunnels last synced "
                               f"{self.tunneler.seconds_since_sync()}s "
                               f"ago (limit 600)")))

    @property
    def url(self) -> str:
        return self.server.url

    @property
    def port(self) -> int:
        return self.server.port

    # ------------------------------------------------ bootstrap loops
    # (ref: pkg/master/controller.go — the core controller that creates
    # the "default" namespace and the "kubernetes" master service, and
    # reconciles that service's endpoints to the live apiservers)

    def _bootstrap_once(self) -> None:
        import ipaddress
        from dataclasses import replace as _replace

        from .core import types as api
        from .core.errors import AlreadyExists, NotFound

        # 1. the namespace holding the master services (:133
        # CreateNamespaceIfNeeded)
        try:
            self.registry.create("namespaces", api.Namespace(
                metadata=api.ObjectMeta(name="default")))
        except AlreadyExists:
            pass
        # 2. the kubernetes service on the first IP of the service range
        # (:187 CreateOrUpdateMasterServiceIfNeeded; the reference pins
        # the range's base address)
        net = ipaddress.ip_network(self.config.service_cidr)
        master_ip = str(net.network_address + 1)
        port_name = "https" if self.config.tls_cert_file else "http"
        try:
            self.registry.get("services", "kubernetes", "default")
        except NotFound:
            try:
                self.registry.create("services", api.Service(
                    metadata=api.ObjectMeta(name="kubernetes",
                                            namespace="default",
                                            labels={"component":
                                                    "apiserver",
                                                    "provider":
                                                    "kubernetes"}),
                    spec=api.ServiceSpec(
                        cluster_ip=master_ip,
                        session_affinity="ClientIP",
                        ports=[api.ServicePort(name=port_name,
                                               port=self.server.port)])),
                    "default")
            except AlreadyExists:
                pass
        # 3. endpoints always carry this apiserver (:226
        # ReconcileEndpoints, master_count=1 form: exactly our address)
        want = api.Endpoints(
            metadata=api.ObjectMeta(name="kubernetes",
                                    namespace="default"),
            subsets=[api.EndpointSubset(
                addresses=[api.EndpointAddress(ip=self.config.host)],
                ports=[api.EndpointPort(name=port_name,
                                        port=self.server.port)])])
        try:
            current = self.registry.get("endpoints", "kubernetes",
                                        "default")
            if current.subsets != want.subsets:
                self.registry.update(
                    "endpoints", _replace(current,
                                          subsets=want.subsets),
                    "default")
        except NotFound:
            try:
                self.registry.create("endpoints", want, "default")
            except AlreadyExists:
                pass

    def _bootstrap_loop(self) -> None:
        while not self._bootstrap_stop.wait(10.0):
            try:
                self._bootstrap_once()
            except Exception:
                pass  # next tick retries (crash-only)

    def start(self) -> "Master":
        import threading
        self.server.start()
        self._bootstrap_stop = threading.Event()
        try:
            self._bootstrap_once()
        except Exception:
            pass  # the loop retries
        self._bootstrap_thread = threading.Thread(
            target=self._bootstrap_loop, daemon=True,
            name="master-bootstrap")
        self._bootstrap_thread.start()
        return self

    def stop(self) -> None:
        if getattr(self, "_bootstrap_stop", None) is not None:
            self._bootstrap_stop.set()
        self.server.stop()
        if self.tunneler is not None:
            self.tunneler.stop()
        if self.store is not None and hasattr(self.store, "close"):
            self.store.close()
