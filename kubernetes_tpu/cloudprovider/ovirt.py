"""Wire-real oVirt cloud provider.

Reference: pkg/cloudprovider/providers/ovirt/ovirt.go (286 LoC) — the
smallest real provider: Instances ONLY (Clusters/TCPLoadBalancer/
Zones/Routes all answer "not supported", ovirt.go:117-150), backed by
one REST call: GET <uri>/vms?search=<query> with HTTP basic auth
(newOVirtCloud builds the request URL once, ovirt.go:87-115), XML
response parsed into a hostname-keyed instance map (ovirt.go:196-231):
only VMs whose guest agent reported an fqdn AND whose status/state is
"up" exist as nodes, address = the first guest_info ip.

Config is the reference's gcfg file shape (ovirt.go:52-61):

    [connection]
    uri = https://ovirt.example.com/ovirt-engine/api
    username = admin@internal
    password = secret
    [filters]
    vms = tag=kubernetes
"""

from __future__ import annotations

import base64
import configparser
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Dict, List, Optional

from .cloud import CloudProvider, Instances


class OVirtError(RuntimeError):
    pass


@dataclass
class OVirtInstance:
    """(ref: OVirtInstance ovirt.go:39-44)"""
    uuid: str
    name: str
    ip_address: str


def parse_ovirt_config(text: str) -> dict:
    """The gcfg [connection]/[filters] file (ovirt.go:87-105; username
    defaults to admin@internal, a missing uri is a hard error)."""
    cp = configparser.ConfigParser()
    cp.read_string(text)
    conn = cp["connection"] if cp.has_section("connection") else {}
    uri = conn.get("uri", "")
    if not uri:
        raise OVirtError("missing ovirt uri in cloud provider "
                         "configuration")
    return {
        "uri": uri,
        "username": conn.get("username", "admin@internal"),
        "password": conn.get("password", ""),
        "vms_query": (cp["filters"].get("vms", "")
                      if cp.has_section("filters") else ""),
    }


def parse_vms_xml(text: str) -> Dict[str, OVirtInstance]:
    """<vms><vm id=..><name/><guest_info><fqdn/><ips><ip address=../>
    </ips></guest_info><status><state/></status></vm></vms> ->
    {hostname: instance}, keeping only up VMs with a reported fqdn
    (ref: getInstancesFromXml ovirt.go:196-231)."""
    root = ET.fromstring(text)
    out: Dict[str, OVirtInstance] = {}
    for vm in root.findall("vm"):
        hostname = vm.findtext("guest_info/fqdn", "")
        state = (vm.findtext("status/state", "") or "").lower()
        if not hostname or state != "up":
            continue  # only running, agent-reporting VMs are nodes
        ip = ""
        first = vm.find("guest_info/ips/ip")
        if first is not None:
            ip = first.get("address", "")
        out[hostname] = OVirtInstance(
            uuid=vm.get("id", ""), name=vm.findtext("name", ""),
            ip_address=ip)
    return out


class OVirtInstances(Instances):
    def __init__(self, provider: "OVirtProvider"):
        self._p = provider

    def node_addresses(self, name: str) -> List[str]:
        """(ref: NodeAddresses ovirt.go:152-175 — the guest-reported
        IP; the reference falls back to a DNS lookup of the hostname,
        out of scope for a hermetic provider)"""
        inst = self._p.fetch_instance(name)
        if not inst.ip_address:
            raise OVirtError(f"couldn't find address of {name!r}")
        return [inst.ip_address]

    def external_id(self, name: str) -> str:
        """(ref: ExternalID ovirt.go:177-184 — the VM uuid)"""
        return self._p.fetch_instance(name).uuid

    def instance_id(self, name: str) -> str:
        """(ref: InstanceID ovirt.go:186-194 — '/' + uuid)"""
        return "/" + self._p.fetch_instance(name).uuid

    def list_instances(self, name_filter: str = "") -> List[str]:
        """(ref: List ovirt.go:271-277 — sorted hostnames; the server-
        side vms query already filtered)"""
        names = sorted(self._p.fetch_all_instances())
        if name_filter:
            names = [n for n in names if name_filter in n]
        return names

    def current_node_name(self, hostname: str) -> str:
        return hostname  # ovirt.go:280-282


class OVirtProvider(CloudProvider):
    """(ref: OVirtCloud ovirt.go:47-50 — one prepared VmsRequest)"""

    name = "ovirt"

    def __init__(self, uri: str, username: str = "admin@internal",
                 password: str = "", vms_query: str = "",
                 timeout: float = 15.0):
        base = uri.rstrip("/") + "/vms"
        if vms_query:
            base += "?" + urllib.parse.urlencode({"search": vms_query})
        self.vms_request = base
        self._auth = base64.b64encode(
            f"{username}:{password}".encode()).decode()
        self.timeout = timeout

    @classmethod
    def from_config(cls, text: str) -> "OVirtProvider":
        cfg = parse_ovirt_config(text)
        return cls(cfg["uri"], cfg["username"], cfg["password"],
                   cfg["vms_query"])

    # ------------------------------------------------------------ wire

    def fetch_all_instances(self) -> Dict[str, OVirtInstance]:
        """(ref: fetchAllInstances ovirt.go:233-242)"""
        req = urllib.request.Request(
            self.vms_request,
            headers={"Authorization": f"Basic {self._auth}",
                     "Accept": "application/xml"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return parse_vms_xml(r.read().decode())
        except urllib.error.HTTPError as e:
            raise OVirtError(f"GET {self.vms_request}: HTTP {e.code}")
        except (urllib.error.URLError, OSError, ET.ParseError) as e:
            raise OVirtError(f"GET {self.vms_request}: {e}")

    def fetch_instance(self, name: str) -> OVirtInstance:
        """(ref: fetchInstance ovirt.go:244-256)"""
        inst = self.fetch_all_instances().get(name)
        if inst is None:
            raise OVirtError(f"cannot find instance: {name!r}")
        return inst

    # ------------------------------------------------------- interface

    def instances(self) -> Optional[Instances]:
        return OVirtInstances(self)

    def load_balancers(self):
        return None  # ovirt.go:132-135: not supported

    def zones(self):
        return None  # ovirt.go:142-145

    def routes(self):
        return None  # ovirt.go:147-150
