"""GCE cloud provider — a wire-real client of the compute/v1 REST API.

Reference: pkg/cloudprovider/providers/gce/gce.go (1,653 LoC) — the
provider is a CLIENT of GCE compute/v1: zone-scoped instances and
disks, region-scoped targetPools and forwardingRules, global routes
and firewalls, all JSON over REST with OAuth2 bearer tokens from the
metadata server and ASYNC operations the caller polls to DONE
(gce.go:305-352 waitForOp). This module speaks exactly those shapes —
token fetch, scoped URLs, operation polling — against any endpoint
serving them; in tests, a mock cloud (tests/test_gce_provider.py).
google-api-go-client's role collapses into ~a page of urllib.

Surface parity with gce.go:
  Instances:       List (:1443 — name-filtered zone instances),
                   NodeAddresses (:1390 — networkIP + natIP),
                   ExternalID (:1418 — numeric instance id)
  TCPLoadBalancer: Get/Ensure/Update/Delete (:354-959 — targetPool of
                   instance URLs + forwardingRule carrying the IP +
                   firewall per service; update diffs via
                   addInstance/removeInstance :807)
  Zones:           GetZone (:1535)
  Routes:          ListRoutes/CreateRoute/DeleteRoute (:1475-1533 —
                   global routes, nextHopInstance, cluster-name prefix)
  Disks:           AttachDisk/DetachDisk (:1568-1604 — instance
                   attachDisk/detachDisk verbs), Create/Delete disk
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from .cloud import (CloudProvider, Instances, LoadBalancer, LoadBalancers,
                    Route, Routes, Zone, Zones)


class GceError(RuntimeError):
    pass


class _GceClient:
    """compute/v1 transport: bearer token (metadata-server shaped
    token endpoint), project/zone/region scoping, operation polling."""

    def __init__(self, project: str, zone: str, base_url: str,
                 token_url: str = "", timeout: float = 15.0):
        self.project = project
        self.zone = zone
        # "us-central1-a" -> "us-central1" (gce.go:150 lastIndex('-'))
        self.region = zone.rsplit("-", 1)[0]
        self.base = base_url.rstrip("/")
        self.token_url = token_url
        self.timeout = timeout
        self.token = ""

    def authenticate(self) -> None:
        """(the metadata-server token fetch the reference gets from
        oauth2 ComputeTokenSource)"""
        if not self.token_url:
            return
        req = urllib.request.Request(
            self.token_url, headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                self.token = json.load(r).get("access_token", "")
        except (urllib.error.URLError, OSError) as e:
            raise GceError(f"token fetch: {e}")
        if not self.token:
            raise GceError("metadata server returned no access_token")

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                retry_auth: bool = True) -> Optional[dict]:
        url = f"{self.base}/projects/{self.project}{path}"
        payload = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=payload, method=method,
                                     headers={
                                         "Content-Type": "application/json",
                                         "Authorization":
                                             f"Bearer {self.token}"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read()
                return json.loads(raw) if raw else None
        except urllib.error.HTTPError as e:
            if e.code == 404 and method in ("GET", "DELETE"):
                return None
            if e.code == 401 and retry_auth and self.token_url:
                self.authenticate()
                return self.request(method, path, body, retry_auth=False)
            raise GceError(
                f"{method} {path}: HTTP {e.code} "
                f"{e.read().decode(errors='replace')[:200]}")
        except (urllib.error.URLError, OSError) as e:
            raise GceError(f"{method} {path}: {e}")

    # ---- async operations (gce.go:305-352) ----

    def wait_op(self, op: Optional[dict], max_polls: int = 100,
                poll_interval: float = 0.5) -> None:
        """Poll a returned Operation to DONE, surfacing its error
        (gce.go waitForOp + opIsDone/getErrorFromOp). Sleeps between
        polls like the reference — back-to-back GETs would exhaust
        max_polls in under a second for an operation that takes a few
        seconds to land, spuriously failing the mutation AND hammering
        the API ~100 times."""
        if op is None:
            return
        name = op.get("name", "")
        scope = op.get("zone") or op.get("region")
        for i in range(max_polls):
            if op and op.get("status") == "DONE":
                err = (op.get("error") or {}).get("errors")
                if err:
                    raise GceError(f"operation {name}: {err[0]}")
                return
            if scope:
                kind = "zones" if "zones/" in scope else "regions"
                seg = scope.rsplit("/", 1)[-1]
                path = f"/{kind}/{seg}/operations/{name}"
            else:
                path = f"/global/operations/{name}"
            if i:
                time.sleep(poll_interval)
            op = self.request("GET", path) or {}
        raise GceError(f"operation {name}: did not reach DONE")

    # ---- URL builders (gce.go:283-303 makeHostURL/targetPoolURL) ----

    def instance_url(self, name: str) -> str:
        return (f"{self.base}/projects/{self.project}/zones/{self.zone}"
                f"/instances/{name}")

    def target_pool_url(self, name: str) -> str:
        return (f"{self.base}/projects/{self.project}/regions/"
                f"{self.region}/targetPools/{name}")

    def disk_url(self, name: str) -> str:
        return (f"{self.base}/projects/{self.project}/zones/{self.zone}"
                f"/disks/{name}")


class GceInstances(Instances):
    def __init__(self, client: _GceClient):
        self._c = client

    def _get(self, name: str) -> dict:
        inst = self._c.request(
            "GET", f"/zones/{self._c.zone}/instances/{name}")
        if inst is None:
            raise KeyError(f"instance {name!r} not found")
        return inst

    def list_instances(self, name_filter: str = "") -> List[str]:
        """(gce.go:1443 List — server-side name eq filter)"""
        q = ""
        if name_filter:
            q = "?filter=" + urllib.parse.quote(
                f"name eq {name_filter}")
        data = self._c.request(
            "GET", f"/zones/{self._c.zone}/instances{q}") or {}
        return sorted(i.get("name", "") for i in data.get("items", []))

    def node_addresses(self, name: str) -> List[str]:
        """(gce.go:1390 — the primary interface's networkIP, then its
        NAT access-config IP)"""
        inst = self._get(name)
        nics = inst.get("networkInterfaces") or []
        out: List[str] = []
        if nics:
            ip = nics[0].get("networkIP")
            if ip:
                out.append(ip)
            for ac in nics[0].get("accessConfigs") or []:
                nat = ac.get("natIP")
                if nat and nat not in out:
                    out.append(nat)
        return out

    def external_id(self, name: str) -> str:
        return str(self._get(name).get("id", ""))


class GceLoadBalancers(LoadBalancers):
    """targetPool + forwardingRule + firewall per LB
    (gce.go:354-959)."""

    def __init__(self, client: _GceClient):
        self._c = client

    def _rule(self, name: str) -> Optional[dict]:
        return self._c.request(
            "GET", f"/regions/{self._c.region}/forwardingRules/{name}")

    def _pool(self, name: str) -> Optional[dict]:
        return self._c.request(
            "GET", f"/regions/{self._c.region}/targetPools/{name}")

    @staticmethod
    def _instance_names(pool: Optional[dict]) -> List[str]:
        return sorted(u.rsplit("/", 1)[-1]
                      for u in (pool or {}).get("instances", []))

    def _lb_of(self, rule: dict, region: str) -> LoadBalancer:
        name = rule.get("name", "")
        # a forwarding rule only carries a portRange, not the service's
        # port list (gce.go:500 likewise can only compare the range) —
        # the exact list the controller diffs against rides the rule's
        # description field, a GCE-sanctioned metadata slot (later
        # reference versions store service identity there too)
        ports: List[int] = []
        try:
            ports = [int(p) for p in json.loads(
                rule.get("description", "") or "{}").get("ports", [])]
        except (ValueError, AttributeError):
            pass
        if not ports:
            pr = rule.get("portRange", "")
            lo = pr.split("-")[0] if pr else ""
            ports = [int(lo)] if lo else []
        return LoadBalancer(
            name=name, region=region,
            external_ip=rule.get("IPAddress", ""),
            ports=sorted(ports),
            hosts=self._instance_names(self._pool(name)))

    def get(self, name: str, region: str) -> Optional[LoadBalancer]:
        """(gce.go:354 GetTCPLoadBalancer — the forwarding rule IS the
        existence signal; its IP is the status)"""
        rule = self._rule(name)
        return self._lb_of(rule, region) if rule is not None else None

    def list(self) -> List[LoadBalancer]:
        data = self._c.request(
            "GET", f"/regions/{self._c.region}/forwardingRules") or {}
        return [self._lb_of(r, self._c.region)
                for r in data.get("items", [])]

    def ensure(self, name: str, region: str, ports: List[int],
               hosts: List[str],
               load_balancer_ip: str = "") -> LoadBalancer:
        """(gce.go:380 EnsureTCPLoadBalancer — target pool of instance
        URLs, forwarding rule over the pool's port range, firewall
        allowing the service ports; each mutation is an async op.
        load_balancer_ip rides the forwarding rule's IPAddress, the
        requested-address seat gce.go passes through)"""
        existing = self.get(name, region)
        if existing is not None:
            if sorted(existing.ports) != sorted(ports):
                # a forwarding rule's port range is immutable — the
                # reference deletes and recreates on mismatch
                # (gce.go:500 forwardingRuleNeedsUpdate -> :427 delete
                # + recreate path)
                self.delete(name, region)
            else:
                self.update_hosts(name, region, hosts)
                got = self.get(name, region)
                assert got is not None
                return got
        if not ports:
            raise GceError("no ports specified for GCE load balancer")
        port_range = f"{min(ports)}-{max(ports)}"  # gce.go:616-637
        self._c.wait_op(self._c.request(
            "POST", f"/regions/{self._c.region}/targetPools", {
                "name": name,
                "instances": [self._c.instance_url(h) for h in hosts],
                "sessionAffinity": "NONE"}))
        self._c.wait_op(self._c.request(
            "POST", f"/regions/{self._c.region}/forwardingRules", {
                "name": name, "IPProtocol": "TCP",
                **({"IPAddress": load_balancer_ip}
                   if load_balancer_ip else {}),
                "portRange": port_range,
                "description": json.dumps(
                    {"ports": sorted(ports)}),
                "target": self._c.target_pool_url(name)}))
        self._c.wait_op(self._c.request(
            "POST", "/global/firewalls", {
                "name": f"k8s-fw-{name}",
                "allowed": [{"IPProtocol": "tcp",
                             "ports": [str(p) for p in ports]}],
                "sourceRanges": ["0.0.0.0/0"]}))
        rule = self._rule(name) or {}
        return LoadBalancer(name=name, region=region,
                            external_ip=rule.get("IPAddress", ""),
                            ports=sorted(ports),
                            hosts=sorted(hosts))

    def update_hosts(self, name: str, region: str,
                     hosts: List[str]) -> None:
        """(gce.go:807 UpdateTCPLoadBalancer — diff pool membership
        with addInstance/removeInstance)"""
        pool = self._pool(name)
        if pool is None:
            raise GceError(f"load balancer {name!r} not found")
        have = set(self._instance_names(pool))
        want = set(hosts)
        base = f"/regions/{self._c.region}/targetPools/{name}"
        add = sorted(want - have)
        remove = sorted(have - want)
        if add:
            self._c.wait_op(self._c.request(
                "POST", f"{base}/addInstance", {
                    "instances": [{"instance": self._c.instance_url(h)}
                                  for h in add]}))
        if remove:
            self._c.wait_op(self._c.request(
                "POST", f"{base}/removeInstance", {
                    "instances": [{"instance": self._c.instance_url(h)}
                                  for h in remove]}))

    def delete(self, name: str, region: str) -> None:
        """(gce.go:868 EnsureTCPLoadBalancerDeleted — forwarding rule,
        then target pool, then the firewall)"""
        rule = self._rule(name)
        if rule is not None:
            self._c.wait_op(self._c.request(
                "DELETE",
                f"/regions/{self._c.region}/forwardingRules/{name}"))
        if self._pool(name) is not None:
            self._c.wait_op(self._c.request(
                "DELETE",
                f"/regions/{self._c.region}/targetPools/{name}"))
        self._c.request("DELETE", f"/global/firewalls/k8s-fw-{name}")


class GceRoutes(Routes):
    """Global routes with instance next hops (gce.go:1475-1533)."""

    def __init__(self, client: _GceClient, cluster_name: str = "k8s"):
        self._c = client
        self.cluster_name = cluster_name

    def _route_name(self, hint: str) -> str:
        # cluster-prefixed, RFC-1035-ish (the reference names routes
        # <clusterName>-<truncated nameHint>, gce.go:1509)
        safe = "".join(c if c.isalnum() else "-" for c in hint.lower())
        return f"{self.cluster_name}-{safe}"[:63].rstrip("-")

    def list_routes(self, name_filter: str = "") -> List[Route]:
        data = self._c.request("GET", "/global/routes") or {}
        out = []
        for r in data.get("items", []):
            name = r.get("name", "")
            if not name.startswith(f"{self.cluster_name}-"):
                continue  # gce.go:1480 — only this cluster's routes
            if name_filter and name_filter not in name:
                continue
            hop = (r.get("nextHopInstance") or "").rsplit("/", 1)[-1]
            out.append(Route(name=name, target_instance=hop,
                             destination_cidr=r.get("destRange", "")))
        return out

    def create_route(self, route: Route) -> None:
        """(gce.go:1509 — insert a global route, poll the op)"""
        self._c.wait_op(self._c.request("POST", "/global/routes", {
            "name": self._route_name(route.name
                                     or route.destination_cidr),
            "destRange": route.destination_cidr,
            "nextHopInstance":
                self._c.instance_url(route.target_instance),
            "priority": 1000}))

    def delete_route(self, name: str) -> None:
        self._c.wait_op(self._c.request(
            "DELETE", f"/global/routes/{name}"))


class GceProvider(CloudProvider, Zones):
    """(ref: gce.go GCECloud; ProviderName "gce" :238)"""

    name = "gce"

    def __init__(self, project: str, zone: str = "us-central1-a",
                 base_url: str = "https://www.googleapis.com/compute/v1",
                 token_url: str = "", cluster_name: str = "k8s"):
        self._client = _GceClient(project, zone, base_url, token_url)
        self._client.authenticate()
        self._instances = GceInstances(self._client)
        self._load_balancers = GceLoadBalancers(self._client)
        self._routes = GceRoutes(self._client, cluster_name)

    def instances(self) -> Optional[Instances]:
        return self._instances

    def load_balancers(self) -> Optional[LoadBalancers]:
        return self._load_balancers

    def zones(self) -> Optional[Zones]:
        return self

    def get_zone(self) -> Zone:
        # ref: gce.go:1535 — the configured zone + derived region
        return Zone(failure_domain=self._client.zone,
                    region=self._client.region)

    def routes(self) -> Optional[Routes]:
        return self._routes  # ref: gce.go:272

    # ------------------------------------------------------- PD volumes

    def attach_disk(self, disk_name: str, node: str) -> None:
        """(gce.go:1568 AttachDisk — the instance attachDisk verb with
        the zone disk's source URL)"""
        self._client.wait_op(self._client.request(
            "POST",
            f"/zones/{self._client.zone}/instances/{node}/attachDisk", {
                "deviceName": disk_name,
                "source": self._client.disk_url(disk_name),
                "mode": "READ_WRITE"}))

    def detach_disk(self, disk_name: str, node: str) -> None:
        """(gce.go:1587 DetachDisk — deviceName query param)"""
        self._client.wait_op(self._client.request(
            "POST",
            f"/zones/{self._client.zone}/instances/{node}/detachDisk"
            f"?deviceName={urllib.parse.quote(disk_name)}"))

    def create_disk(self, name: str, size_gb: int) -> None:
        """(gce.go CreateDisk — zone disks insert)"""
        self._client.wait_op(self._client.request(
            "POST", f"/zones/{self._client.zone}/disks", {
                "name": name, "sizeGb": str(size_gb)}))

    def delete_disk(self, name: str) -> None:
        self._client.wait_op(self._client.request(
            "DELETE", f"/zones/{self._client.zone}/disks/{name}"))
