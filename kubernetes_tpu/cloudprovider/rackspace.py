"""Wire-real Rackspace cloud provider.

Reference: pkg/cloudprovider/providers/rackspace/rackspace.go (388
LoC) — OpenStack-derived but NOT the same provider: auth goes to the
Rackspace identity service where an api-key maps to the RAX-KSKEY
apiKeyCredentials extension (Config.Global.ApiKey ->
gophercloud.AuthOptions.APIKey, rackspace.go:101-114; password auth
remains the fallback), and only Instances + Zones are supported
(TCPLoadBalancer/Routes answer "not supported", rackspace.go:370-382).

Instance lookups carry the reference's quirks faithfully:
- List filters server-side by name AND Status=ACTIVE
  (rackspace.go:161-166).
- getServerByName treats an IP-shaped name as an ADDRESS lookup
  (rackspace.go:239-241 -> getServerByAddress :206), matching against
  the first private addr, first public addr, accessIPv4, accessIPv6
  (serverHasAddress :190-204); more than one match is an error.
- Otherwise the name matches as an ANCHORED case-insensitive regex
  over the server list (gophercloud's rackspace servers list; the
  multiple-results error is kept).
- NodeAddresses = first private addr, else first public, else
  accessIPv4, else accessIPv6 (getAddressByName :298-321, firstAddr
  :277-296 reads the runtime-typed address blob).
"""

from __future__ import annotations

import ipaddress
import re
import urllib.parse
from typing import List, Optional

from .cloud import CloudProvider, Instances, Zone, Zones
from .openstack import OpenStackError, _Session


class RackspaceError(RuntimeError):
    pass


class _RackspaceSession(_Session):
    """Keystone v2 session whose auth body speaks the RAX-KSKEY
    apiKeyCredentials extension when an api key is configured
    (rackspace.go toAuthOptions maps ApiKey; password is the
    fallback)."""

    def __init__(self, auth_url: str, username: str, api_key: str = "",
                 password: str = "", tenant: str = "",
                 timeout: float = 15.0, region: str = ""):
        super().__init__(auth_url, username, password, tenant,
                         timeout=timeout, region=region)
        self.api_key = api_key

    def authenticate(self) -> None:
        if not self.api_key:
            return super().authenticate()
        body = {"auth": {
            "RAX-KSKEY:apiKeyCredentials": {
                "username": self.username, "apiKey": self.api_key}}}
        if self.tenant:
            body["auth"]["tenantName"] = self.tenant
        data = self._raw_request("POST", self.auth_url + "/tokens",
                                 body, token=False)
        self._consume_access(data)


def _first_addr(netblob) -> str:
    """(ref: firstAddr rackspace.go:277-296 — the runtime-typed
    addresses blob: [{'addr': ...}, ...])"""
    if not isinstance(netblob, list) or not netblob:
        return ""
    props = netblob[0]
    if not isinstance(props, dict):
        return ""
    addr = props.get("addr", "")
    return addr if isinstance(addr, str) else ""


def _server_address(srv: dict) -> str:
    """(ref: getAddressByName rackspace.go:298-321 address ladder)"""
    addresses = srv.get("addresses", {}) or {}
    for blob in (addresses.get("private"), addresses.get("public")):
        addr = _first_addr(blob)
        if addr:
            return addr
    return srv.get("accessIPv4", "") or srv.get("accessIPv6", "")


def _server_has_address(srv: dict, ip: str) -> bool:
    """(ref: serverHasAddress rackspace.go:190-204)"""
    addresses = srv.get("addresses", {}) or {}
    return ip in (
        _first_addr(addresses.get("private")),
        _first_addr(addresses.get("public")),
        srv.get("accessIPv4", ""),
        srv.get("accessIPv6", ""))


class RackspaceInstances(Instances):
    def __init__(self, session: _RackspaceSession):
        self._s = session

    def _list_servers(self, name_filter: str = "") -> List[dict]:
        path = "/servers/detail"
        if name_filter:
            path += "?" + urllib.parse.urlencode(
                {"name": name_filter, "status": "ACTIVE"})
        data = self._s.request("GET", "compute", path) or {}
        return data.get("servers", [])

    def _server_by_name(self, name: str) -> dict:
        """(ref: getServerByName rackspace.go:239-275 — IP-shaped
        names resolve by address; otherwise anchored ci regex, with
        multiple matches an error)"""
        try:
            ipaddress.ip_address(name)
        except ValueError:
            pass
        else:
            return self._server_by_address(name)
        pattern = re.compile(f"^{re.escape(name)}$", re.IGNORECASE)
        matches = [s for s in self._list_servers(name)
                   if pattern.match(s.get("name", ""))]
        if not matches:
            raise RackspaceError(f"instance {name!r} not found")
        if len(matches) > 1:
            raise RackspaceError(f"multiple results for {name!r}")
        return matches[0]

    def _server_by_address(self, ip: str) -> dict:
        """(ref: getServerByAddress rackspace.go:206-237)"""
        matches = [s for s in self._list_servers()
                   if _server_has_address(s, ip)]
        if not matches:
            raise RackspaceError(f"no instance with address {ip!r}")
        if len(matches) > 1:
            raise RackspaceError(f"multiple results for {ip!r}")
        return matches[0]

    def node_addresses(self, name: str) -> List[str]:
        addr = _server_address(self._server_by_name(name))
        if not addr:
            raise RackspaceError(f"no address found for {name!r}")
        return [addr]

    def external_id(self, name: str) -> str:
        return self._server_by_name(name).get("id", "")

    def instance_id(self, name: str) -> str:
        return self._server_by_name(name).get("id", "")

    def list_instances(self, name_filter: str = "") -> List[str]:
        """(ref: List rackspace.go:161-189 — server-side name +
        ACTIVE-status filter)"""
        return [s.get("name", "")
                for s in self._list_servers(name_filter)
                if s.get("status", "ACTIVE") == "ACTIVE"]

    def current_node_name(self, hostname: str) -> str:
        return hostname  # rackspace.go:352-354


class RackspaceProvider(CloudProvider, Zones):
    """(ref: Rackspace rackspace.go:127-144; only Instances + Zones
    are supported, rackspace.go:356-388)"""

    name = "rackspace"

    def __init__(self, auth_url: str, username: str, api_key: str = "",
                 password: str = "", tenant: str = "", region: str = ""):
        self._session = _RackspaceSession(
            auth_url, username, api_key=api_key, password=password,
            tenant=tenant, region=region)
        self._session.authenticate()
        self.region = region

    def instances(self) -> Optional[Instances]:
        return RackspaceInstances(self._session)

    def load_balancers(self):
        return None  # rackspace.go:370-372: not supported

    def zones(self) -> Optional[Zones]:
        return self

    def get_zone(self) -> Zone:
        """(ref: GetZone rackspace.go:384-388 — the configured region,
        no failure domain)"""
        return Zone(failure_domain="", region=self.region)

    def routes(self):
        return None  # rackspace.go:380-382
