"""Cloud provider interfaces and the in-memory fake.

Reference: pkg/cloudprovider/cloud.go:
    Interface { TCPLoadBalancer() Instances() Zones() Routes() }
and pkg/cloudprovider/providers/fake/fake.go (call-recording fake).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class LoadBalancer:
    name: str = ""
    region: str = ""
    external_ip: str = ""
    ports: List[int] = field(default_factory=list)
    hosts: List[str] = field(default_factory=list)


@dataclass
class Zone:
    failure_domain: str = ""
    region: str = ""


@dataclass
class Route:
    name: str = ""
    target_instance: str = ""
    destination_cidr: str = ""


class Instances:
    def node_addresses(self, name: str) -> List[str]:
        raise NotImplementedError

    def external_id(self, name: str) -> str:
        raise NotImplementedError

    def list_instances(self, name_filter: str = "") -> List[str]:
        raise NotImplementedError


class LoadBalancers:
    """(ref: cloud.go TCPLoadBalancer interface)"""

    def get(self, name: str, region: str) -> Optional[LoadBalancer]:
        raise NotImplementedError

    def list(self) -> List[LoadBalancer]:
        """All balancers this provider manages (for orphan GC)."""
        raise NotImplementedError

    # whether ensure() can honor a requested address at all — AWS
    # classic ELBs cannot (aws.go rejects a requested publicIP); the
    # controller consults this BEFORE tearing anything down
    supports_load_balancer_ip: bool = True

    def ensure(self, name: str, region: str, ports: List[int],
               hosts: List[str],
               load_balancer_ip: str = "") -> LoadBalancer:
        """load_balancer_ip: the service's requested address (ref:
        EnsureTCPLoadBalancer's externalIP parameter) — honored by
        providers that support address reservation, best-effort
        elsewhere."""
        raise NotImplementedError

    def update_hosts(self, name: str, region: str,
                     hosts: List[str]) -> None:
        raise NotImplementedError

    def delete(self, name: str, region: str) -> None:
        raise NotImplementedError


class Zones:
    def get_zone(self) -> Zone:
        raise NotImplementedError


class Routes:
    def list_routes(self, name_filter: str = "") -> List[Route]:
        raise NotImplementedError

    def create_route(self, route: Route) -> None:
        raise NotImplementedError

    def delete_route(self, name: str) -> None:
        raise NotImplementedError


class CloudProvider:
    """(ref: cloud.go Interface; any facet may be unsupported -> None)"""

    def instances(self) -> Optional[Instances]:
        return None

    def load_balancers(self) -> Optional[LoadBalancers]:
        return None

    def zones(self) -> Optional[Zones]:
        return None

    def routes(self) -> Optional[Routes]:
        return None

    # cloud disk attach surface used by the volume plugins
    def attach_disk(self, disk_name: str, node: str) -> None:
        raise NotImplementedError

    def detach_disk(self, disk_name: str, node: str) -> None:
        raise NotImplementedError


class FakeCloudProvider(CloudProvider, Instances, LoadBalancers, Zones,
                        Routes):
    """Records every call; serves canned data (ref: fake/fake.go)."""

    def __init__(self, zone: str = "us-central1-a",
                 region: str = "us-central1"):
        self.zone = zone
        self.region = region
        self.calls: List[str] = []
        self.balancers: Dict[Tuple[str, str], LoadBalancer] = {}
        self.routes_by_name: Dict[str, Route] = {}
        self.attached: Dict[str, str] = {}  # disk -> node
        self.instance_list: List[str] = []
        self._ip_counter = 0
        self._lock = threading.Lock()

    # facets
    def instances(self):
        return self

    def load_balancers(self):
        return self

    def zones(self):
        return self

    def routes(self):
        return self

    # Instances
    def node_addresses(self, name: str) -> List[str]:
        self.calls.append(f"node-addresses:{name}")
        return ["10.1.0.1"]

    def external_id(self, name: str) -> str:
        self.calls.append(f"external-id:{name}")
        return f"ext-{name}"

    def list_instances(self, name_filter: str = "") -> List[str]:
        return [i for i in self.instance_list if name_filter in i]

    # LoadBalancers
    def get(self, name: str, region: str) -> Optional[LoadBalancer]:
        with self._lock:
            return self.balancers.get((name, region))

    def list(self) -> List[LoadBalancer]:
        with self._lock:
            return list(self.balancers.values())

    def ensure(self, name: str, region: str, ports: List[int],
               hosts: List[str],
               load_balancer_ip: str = "") -> LoadBalancer:
        self.calls.append(f"ensure-lb:{name}")
        with self._lock:
            lb = self.balancers.get((name, region))
            if lb is None:
                self._ip_counter += 1
                lb = LoadBalancer(name=name, region=region,
                                  external_ip=(load_balancer_ip
                                               or f"35.0.0.{self._ip_counter}"))
                self.balancers[(name, region)] = lb
            lb.ports = list(ports)
            lb.hosts = list(hosts)
            return lb

    def update_hosts(self, name: str, region: str,
                     hosts: List[str]) -> None:
        self.calls.append(f"update-hosts:{name}")
        with self._lock:
            lb = self.balancers.get((name, region))
            if lb is not None:
                lb.hosts = list(hosts)

    def delete(self, name: str, region: str) -> None:
        self.calls.append(f"delete-lb:{name}")
        with self._lock:
            self.balancers.pop((name, region), None)

    # Zones
    def get_zone(self) -> Zone:
        return Zone(failure_domain=self.zone, region=self.region)

    # Routes
    def list_routes(self, name_filter: str = "") -> List[Route]:
        with self._lock:
            return [r for r in self.routes_by_name.values()
                    if name_filter in r.name]

    def create_route(self, route: Route) -> None:
        self.calls.append(f"create-route:{route.name}")
        with self._lock:
            self.routes_by_name[route.name] = route

    def delete_route(self, name: str) -> None:
        self.calls.append(f"delete-route:{name}")
        with self._lock:
            self.routes_by_name.pop(name, None)

    # disks
    def attach_disk(self, disk_name: str, node: str) -> None:
        self.calls.append(f"attach:{disk_name}:{node}")
        with self._lock:
            self.attached[disk_name] = node

    def detach_disk(self, disk_name: str, node: str) -> None:
        self.calls.append(f"detach:{disk_name}:{node}")
        with self._lock:
            self.attached.pop(disk_name, None)
