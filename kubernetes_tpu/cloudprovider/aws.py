"""AWS cloud provider — a wire-real client of the EC2/ELB Query APIs.

Reference: pkg/cloudprovider/providers/aws/aws.go (2,111 LoC) — the
provider is a CLIENT of EC2 (DescribeInstances :302, volumes
:350-380, security groups :1305-1566, route tables) and ELB
(CreateLoadBalancer/RegisterInstances/DeleteLoadBalancer :384-440,
used by :1627-1965). The AWS wire protocol is the Query API:
form-encoded `Action=...` POSTs signed with Signature V4, answered in
XML. This module speaks exactly that — a real SigV4 signing chain
(hashlib/hmac), dotted-index parameter flattening
(`Listeners.member.1.LoadBalancerPort`), ElementTree responses — so
it runs against any endpoint serving the shapes; in tests, a mock
cloud (tests/test_aws_provider.py). The aws-sdk-go role collapses
into ~a page of urllib.

Surface parity with aws.go:
  Instances:        List (:775 regex over running instances),
                    NodeAddresses (:620 private-dns lookup -> private
                    then public IP), ExternalID (:673 instance id)
  TCPLoadBalancer:  Get/Ensure/Update/Delete (:1627-1965 — security
                    group ingress per port, one ELB listener per
                    (port, nodePort), register/deregister diff;
                    status carries the ELB DNS name :1798)
  Zones:            GetZone (:781 — the configured AZ)
  Routes:           route tables (routes.go — CreateRoute with
                    DestinationCidrBlock + InstanceId)
  Disks:            AttachVolume/DetachVolume/CreateVolume/
                    DeleteVolume (:1100-1256, EBS)
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

from .cloud import (CloudProvider, Instances, LoadBalancer, LoadBalancers,
                    Route, Routes, Zone, Zones)

EC2_VERSION = "2014-10-01"   # aws-sdk-go ec2 API version of the era
ELB_VERSION = "2012-06-01"


class AwsError(RuntimeError):
    pass


def _flatten(params: dict, prefix: str = "") -> Dict[str, str]:
    """AWS Query dotted-index encoding: lists become Name.N[.member],
    dicts nest with dots — {'Filter': [{'Name': 'x', 'Value': ['a']}]}
    -> Filter.1.Name=x & Filter.1.Value.1=a."""
    out: Dict[str, str] = {}
    for key, val in params.items():
        full = f"{prefix}{key}"
        if isinstance(val, dict):
            out.update(_flatten(val, full + "."))
        elif isinstance(val, (list, tuple)):
            for i, item in enumerate(val, 1):
                if isinstance(item, dict):
                    out.update(_flatten(item, f"{full}.{i}."))
                else:
                    out[f"{full}.{i}"] = str(item)
        else:
            out[full] = str(val)
    return out


def _strip_ns(root: ET.Element) -> ET.Element:
    """AWS XML carries a default namespace; strip it so finds are
    plain-tag (the response shapes, not the namespaces, are the API)."""
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


class _QueryClient:
    """Signed AWS Query API transport: SigV4 over form-encoded POST.

    endpoints: service -> base URL (tests point at the mock cloud; a
    real deployment uses https://{service}.{region}.amazonaws.com)."""

    def __init__(self, access_key: str, secret_key: str, region: str,
                 endpoints: Dict[str, str], timeout: float = 15.0):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.endpoints = {k: v.rstrip("/") for k, v in endpoints.items()}
        self.timeout = timeout

    # ---- Signature Version 4 (the real chain, not a stub) ----

    def _sign(self, service: str, host: str, body: bytes,
              amz_date: str) -> str:
        date = amz_date[:8]
        scope = f"{date}/{self.region}/{service}/aws4_request"
        canonical = "\n".join([
            "POST", "/", "",
            f"host:{host}\nx-amz-date:{amz_date}\n",
            "host;x-amz-date",
            hashlib.sha256(body).hexdigest()])
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

        def h(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = h(h(h(h(b"AWS4" + self.secret_key.encode(), date),
                  self.region), service), "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        return (f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
                f"SignedHeaders=host;x-amz-date, Signature={sig}")

    def call(self, service: str, action: str,
             params: Optional[dict] = None) -> ET.Element:
        url = self.endpoints.get(service)
        if not url:
            raise AwsError(f"no endpoint configured for {service!r}")
        version = EC2_VERSION if service == "ec2" else ELB_VERSION
        form = {"Action": action, "Version": version}
        form.update(_flatten(params or {}))
        body = urllib.parse.urlencode(sorted(form.items())).encode()
        host = urllib.parse.urlsplit(url).netloc
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y%m%dT%H%M%SZ")
        req = urllib.request.Request(url, data=body, method="POST", headers={
            "Content-Type": "application/x-www-form-urlencoded",
            "X-Amz-Date": amz_date,
            "Authorization": self._sign(service, host, body, amz_date)})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return _strip_ns(ET.fromstring(r.read()))
        except urllib.error.HTTPError as e:
            raw = e.read().decode(errors="replace")
            code, msg = e.code, raw[:200]
            try:
                err = _strip_ns(ET.fromstring(raw))
                code = err.findtext(".//Code") or code
                msg = err.findtext(".//Message") or msg
            except ET.ParseError:
                pass
            raise AwsError(f"{action}: {code}: {msg}")
        except (urllib.error.URLError, OSError) as e:
            raise AwsError(f"{action}: {e}")


class AwsInstances(Instances):
    def __init__(self, client: _QueryClient):
        self._c = client

    def _describe(self, extra_filters: Optional[list] = None
                  ) -> List[ET.Element]:
        """Running instances only (aws.go:729 instance-state-name
        filter — terminated instances linger in DescribeInstances)."""
        filters = [{"Name": "instance-state-name", "Value": ["running"]}]
        filters += extra_filters or []
        root = self._c.call("ec2", "DescribeInstances",
                            {"Filter": filters})
        return root.findall(".//reservationSet/item/instancesSet/item")

    def _by_node_name(self, name: str) -> ET.Element:
        """Node name -> instance via the private-dns-name filter
        (aws.go:838 findInstanceByNodeName)."""
        items = self._describe(
            [{"Name": "private-dns-name", "Value": [name]}])
        if not items:
            raise KeyError(f"instance {name!r} not found")
        if len(items) > 1:
            raise AwsError(f"multiple instances found for {name!r}")
        return items[0]

    def list_instances(self, name_filter: str = "") -> List[str]:
        import re
        rx = re.compile(name_filter) if name_filter else None
        out = []
        for inst in self._describe():
            name = inst.findtext("privateDnsName") or ""
            if name and (rx is None or rx.match(name)):
                out.append(name)
        return sorted(out)

    def node_addresses(self, name: str) -> List[str]:
        """(aws.go:620 — internal/private address first, then the
        public one when present)"""
        inst = self._by_node_name(name)
        out = []
        for tag in ("privateIpAddress", "ipAddress"):
            addr = inst.findtext(tag)
            if addr and addr not in out:
                out.append(addr)
        return out

    def external_id(self, name: str) -> str:
        return self._by_node_name(name).findtext("instanceId") or ""

    def instance_ids(self, names: List[str]) -> List[str]:
        return [self.external_id(n) for n in names]


class AwsLoadBalancers(LoadBalancers):
    """ELB classic (ref: aws.go:1627-1965 + the awsSdkELB calls
    :384-440)."""

    # classic ELBs allocate their own DNS address; the controller must
    # not tear anything down chasing a requested IP (aws.go rejects a
    # requested publicIP up front)
    supports_load_balancer_ip = False

    def __init__(self, client: _QueryClient, instances: AwsInstances,
                 vpc_id: str = "vpc-default", zone: str = ""):
        self._c = client
        self._i = instances
        self.vpc_id = vpc_id
        # the AZ the cluster's instances live in (aws.go derives the
        # ELB's zones from the instances; this single-zone provider
        # carries it as config) — an ELB enabled only in a hardcoded
        # {region}a would leave instances in any other zone
        # OutOfService with no backends
        self.zone = zone or f"{client.region}a"

    def _describe(self, name: str) -> Optional[ET.Element]:
        try:
            root = self._c.call("elb", "DescribeLoadBalancers",
                                {"LoadBalancerNames": {"member": [name]}})
        except AwsError as e:
            if "LoadBalancerNotFound" in str(e):
                return None
            raise
        return root.find(".//LoadBalancerDescriptions/member")

    def _id_to_node_map(self) -> Dict[str, str]:
        out = {}
        for inst in self._i._describe():
            iid = inst.findtext("instanceId")
            if iid:
                out[iid] = inst.findtext("privateDnsName") or iid
        return out

    def _lb_of(self, desc: ET.Element, region: str,
               id_to_node: Optional[Dict[str, str]] = None
               ) -> LoadBalancer:
        name = desc.findtext("LoadBalancerName") or ""
        ports = sorted(int(p.text) for p in desc.findall(
            ".//ListenerDescriptions/member/Listener/LoadBalancerPort"))
        ids = [i.findtext("InstanceId")
               for i in desc.findall(".//Instances/member")]
        # hosts are NODE NAMES in the cloudprovider contract (the
        # service controller diffs them against node names to decide
        # whether to reconcile) — map ELB's instance IDs back, like
        # aws.go's instance<->node translation everywhere at the API
        # boundary; list() shares one DescribeInstances across all
        # LBs instead of N+1 calls per sync
        if id_to_node is None:
            id_to_node = self._id_to_node_map()
        return LoadBalancer(
            name=name, region=region,
            external_ip=desc.findtext("DNSName") or "",
            ports=ports,
            hosts=sorted(id_to_node.get(i, i)
                         for i in ids if i))

    def get(self, name: str, region: str) -> Optional[LoadBalancer]:
        desc = self._describe(name)
        return self._lb_of(desc, region) if desc is not None else None

    def list(self) -> List[LoadBalancer]:
        root = self._c.call("elb", "DescribeLoadBalancers")
        members = root.findall(".//LoadBalancerDescriptions/member")
        id_to_node = self._id_to_node_map() if members else {}
        return [self._lb_of(d, self._c.region, id_to_node)
                for d in members]

    def _ensure_security_group(self, name: str, ports: List[int]) -> str:
        """(aws.go:1493 ensureSecurityGroup + :1385 ingress rules —
        one world-open TCP permission per service port)"""
        sg_name = f"k8s-elb-{name}"
        try:
            created = self._c.call("ec2", "CreateSecurityGroup", {
                "GroupName": sg_name, "VpcId": self.vpc_id,
                "GroupDescription":
                    f"Security group for Kubernetes ELB {name}"})
            sg_id = created.findtext(".//groupId") or ""
        except AwsError as e:
            if "InvalidGroup.Duplicate" not in str(e):
                raise
            root = self._c.call("ec2", "DescribeSecurityGroups", {
                "Filter": [{"Name": "group-name", "Value": [sg_name]}]})
            sg_id = root.findtext(".//securityGroupInfo/item/groupId") or ""
        # one authorize per port, each tolerating Duplicate: EC2 fails
        # a whole multi-permission authorize when ANY rule pre-exists,
        # and re-ensuring over a leftover group (delete() tolerates SG
        # cleanup races) or a listener change must still land the NEW
        # ports (aws.go ensureSecurityGroupIngress treats
        # already-present as success)
        for p in ports:
            try:
                self._c.call("ec2", "AuthorizeSecurityGroupIngress", {
                    "GroupId": sg_id, "IpPermissions": {"item": [
                        {"IpProtocol": "tcp", "FromPort": p,
                         "ToPort": p,
                         "IpRanges": {"item": [
                             {"CidrIp": "0.0.0.0/0"}]}}]}})
            except AwsError as e:
                if "InvalidPermission.Duplicate" not in str(e):
                    raise
        # reconcile DOWN too: a port removed from the service must not
        # leave its world-open ingress on the group forever
        # (aws.go ensureSecurityGroupIngress removes as well as adds)
        try:
            root = self._c.call("ec2", "DescribeSecurityGroups", {
                "GroupId": [sg_id]})
            for perm in root.findall(".//ipPermissions/item"):
                from_p = perm.findtext("fromPort")
                if from_p is None or int(from_p) in ports:
                    continue
                self._c.call("ec2", "RevokeSecurityGroupIngress", {
                    "GroupId": sg_id, "IpPermissions": {"item": [
                        {"IpProtocol": perm.findtext("ipProtocol")
                         or "tcp",
                         "FromPort": int(from_p),
                         "ToPort": int(perm.findtext("toPort")
                                       or from_p),
                         "IpRanges": {"item": [
                             {"CidrIp": "0.0.0.0/0"}]}}]}})
        except AwsError:
            pass  # stale-rule cleanup is best-effort; adds already landed
        return sg_id

    def ensure(self, name: str, region: str, ports: List[int],
               hosts: List[str],
               load_balancer_ip: str = "") -> LoadBalancer:
        """(aws.go:1627 — region guard, security group, one listener
        per port, register instances; idempotent re-ensure converges
        the host set. A requested load_balancer_ip is REJECTED: classic
        ELBs allocate their own address, and the reference errors on a
        requested publicIP rather than silently ignoring it.)"""
        if load_balancer_ip:
            raise AwsError(
                "requested loadBalancerIP is not supported by "
                "classic ELBs")  # aws.go EnsureTCPLoadBalancer publicIP guard
        if region != self._c.region:
            raise AwsError(
                f"requested load balancer region {region!r} does not "
                f"match cluster region {self._c.region!r}")  # :1630
        desc = self._describe(name)
        if desc is not None:
            have_ports = sorted(int(p.text) for p in desc.findall(
                ".//ListenerDescriptions/member/Listener"
                "/LoadBalancerPort"))
            if have_ports != sorted(ports):
                # listener reconcile (aws.go:1690-1744: the reference
                # diffs listeners and deletes/creates them through the
                # ELB listener verbs)
                if have_ports:
                    self._c.call("elb", "DeleteLoadBalancerListeners", {
                        "LoadBalancerName": name,
                        "LoadBalancerPorts": {"member": have_ports}})
                self._c.call("elb", "CreateLoadBalancerListeners", {
                    "LoadBalancerName": name,
                    "Listeners": {"member": [
                        {"Protocol": "TCP", "LoadBalancerPort": p,
                         "InstanceProtocol": "TCP", "InstancePort": p}
                        for p in ports]}})
                self._ensure_security_group(name, ports)
            self.update_hosts(name, region, hosts)
            got = self.get(name, region)
            assert got is not None
            return got
        sg_id = self._ensure_security_group(name, ports)
        listeners = [{"Protocol": "TCP", "LoadBalancerPort": p,
                      "InstanceProtocol": "TCP", "InstancePort": p}
                     for p in ports]
        created = self._c.call("elb", "CreateLoadBalancer", {
            "LoadBalancerName": name,
            "Listeners": {"member": listeners},
            "AvailabilityZones": {"member": [self.zone]},
            "SecurityGroups": {"member": [sg_id]}})
        dns = created.findtext(".//DNSName") or ""
        ids = self._i.instance_ids(hosts)
        if ids:
            self._c.call("elb", "RegisterInstancesWithLoadBalancer", {
                "LoadBalancerName": name,
                "Instances": {"member": [{"InstanceId": i}
                                         for i in ids]}})
        return LoadBalancer(name=name, region=region, external_ip=dns,
                            ports=sorted(ports), hosts=sorted(hosts))

    def update_hosts(self, name: str, region: str,
                     hosts: List[str]) -> None:
        """(aws.go:1908 UpdateTCPLoadBalancer — register the missing,
        deregister the extra)"""
        desc = self._describe(name)
        if desc is None:
            raise AwsError(f"load balancer {name!r} not found")
        have = {i.findtext("InstanceId")
                for i in desc.findall(".//Instances/member")}
        want = set(self._i.instance_ids(hosts))
        add = sorted(want - have)
        remove = sorted(have - want)
        if add:
            self._c.call("elb", "RegisterInstancesWithLoadBalancer", {
                "LoadBalancerName": name,
                "Instances": {"member": [{"InstanceId": i} for i in add]}})
        if remove:
            self._c.call("elb", "DeregisterInstancesFromLoadBalancer", {
                "LoadBalancerName": name,
                "Instances": {"member": [{"InstanceId": i}
                                         for i in remove]}})

    def delete(self, name: str, region: str) -> None:
        """(aws.go:1838 EnsureTCPLoadBalancerDeleted — the LB, then its
        security group)"""
        if self._describe(name) is not None:
            self._c.call("elb", "DeleteLoadBalancer",
                         {"LoadBalancerName": name})
        try:
            root = self._c.call("ec2", "DescribeSecurityGroups", {
                "Filter": [{"Name": "group-name",
                            "Value": [f"k8s-elb-{name}"]}]})
            sg_id = root.findtext(".//securityGroupInfo/item/groupId")
            if sg_id:
                self._c.call("ec2", "DeleteSecurityGroup",
                             {"GroupId": sg_id})
        except AwsError:
            pass  # the reference also tolerates SG cleanup races :1876


class AwsRoutes(Routes):
    """EC2 route tables (ref: providers/aws/routes.go — routes are
    rows in the cluster's route table keyed by destination CIDR with
    an instance next hop)."""

    def __init__(self, client: _QueryClient, instances: AwsInstances,
                 route_table_id: str):
        self._c = client
        self._i = instances
        self.route_table_id = route_table_id

    def list_routes(self, name_filter: str = "") -> List[Route]:
        """Route rows -> (node, CIDR) pairs. EC2 routes carry instance
        IDs and no names; the reference maps IDs back to node names
        for the controller (aws_routes.go ListRoutes) and the
        controller reconciles on TargetInstance. Route.name is the
        destination CIDR — the row's only EC2-side identity, which
        delete_route takes back."""
        root = self._c.call("ec2", "DescribeRouteTables", {
            "RouteTableId": [self.route_table_id]})
        id_to_node = {}
        for inst in self._i._describe():
            iid = inst.findtext("instanceId")
            if iid:
                id_to_node[iid] = inst.findtext("privateDnsName") or iid
        out = []
        for r in root.findall(".//routeSet/item"):
            inst_id = r.findtext("instanceId")
            cidr = r.findtext("destinationCidrBlock") or ""
            if not inst_id:
                continue  # igw/local rows aren't node routes
            out.append(Route(name=cidr,
                             target_instance=id_to_node.get(inst_id,
                                                            inst_id),
                             destination_cidr=cidr))
        return out

    def create_route(self, route: Route) -> None:
        instance_id = self._i.external_id(route.target_instance)
        self._c.call("ec2", "CreateRoute", {
            "RouteTableId": self.route_table_id,
            "DestinationCidrBlock": route.destination_cidr,
            "InstanceId": instance_id})

    def delete_route(self, name: str) -> None:
        # route identity on EC2 is the destination CIDR
        self._c.call("ec2", "DeleteRoute", {
            "RouteTableId": self.route_table_id,
            "DestinationCidrBlock": name})


class AwsProvider(CloudProvider, Zones):
    """(ref: aws.go AWSCloud; ProviderName "aws" :590)"""

    name = "aws"

    def __init__(self, access_key: str, secret_key: str,
                 region: str = "us-east-1",
                 zone: str = "", endpoints: Optional[Dict[str, str]] = None,
                 route_table_id: str = "rtb-main",
                 vpc_id: str = "vpc-default"):
        self._client = _QueryClient(access_key, secret_key, region,
                                    endpoints or {
                                        "ec2": f"https://ec2.{region}"
                                               f".amazonaws.com",
                                        "elb": f"https://elasticload"
                                               f"balancing.{region}"
                                               f".amazonaws.com"})
        self.region = region
        self.zone = zone or region + "a"
        self._instances = AwsInstances(self._client)
        self._load_balancers = AwsLoadBalancers(self._client,
                                                self._instances, vpc_id,
                                                zone=self.zone)
        self._routes = AwsRoutes(self._client, self._instances,
                                 route_table_id)

    def instances(self) -> Optional[Instances]:
        return self._instances

    def load_balancers(self) -> Optional[LoadBalancers]:
        return self._load_balancers

    def zones(self) -> Optional[Zones]:
        return self

    def get_zone(self) -> Zone:
        # ref: aws.go:781 — the configured availability zone
        return Zone(failure_domain=self.zone, region=self.region)

    def routes(self) -> Optional[Routes]:
        return self._routes  # ref: aws.go:615

    # ------------------------------------------------------ EBS volumes

    def attach_disk(self, disk_name: str, node: str) -> None:
        """(aws.go:1100 AttachDisk — EBS AttachVolume with the next
        device free ON THE INSTANCE; the reference scans the
        instance's block-device mappings for the same reason: two
        volumes on one node must not both claim /dev/xvdf)"""
        instance_id = self._instances.external_id(node)
        root = self._c("ec2", "DescribeVolumes", {"Filter": [
            {"Name": "attachment.instance-id",
             "Value": [instance_id]}]})
        used = {a.findtext("device")
                for a in root.findall(".//attachmentSet/item")
                if a.findtext("instanceId") == instance_id}
        device = next((f"/dev/xvd{c}" for c in "fghijklmnop"
                       if f"/dev/xvd{c}" not in used), None)
        if device is None:
            raise AwsError(
                f"no free EBS device letter on {node!r} (f..p all used)")
        self._c("ec2", "AttachVolume", {
            "VolumeId": disk_name, "InstanceId": instance_id,
            "Device": device})

    def detach_disk(self, disk_name: str, node: str) -> None:
        """(aws.go:1169 DetachDisk)"""
        instance_id = self._instances.external_id(node)
        self._c("ec2", "DetachVolume", {
            "VolumeId": disk_name, "InstanceId": instance_id})

    def create_volume(self, size_gb: int) -> str:
        """(aws.go:1219 CreateVolume -> volume id)"""
        root = self._c("ec2", "CreateVolume", {
            "AvailabilityZone": self.zone, "Size": size_gb})
        return root.findtext(".//volumeId") or ""

    def delete_volume(self, volume_id: str) -> None:
        """(aws.go:1241 DeleteVolume)"""
        self._c("ec2", "DeleteVolume", {"VolumeId": volume_id})

    def _c(self, service: str, action: str, params: dict) -> ET.Element:
        return self._client.call(service, action, params)
