"""Cloud provider interface + fake.

Reference: pkg/cloudprovider/cloud.go (Interface: Instances,
LoadBalancers (TCPLoadBalancer at v1.1), Zones, Routes) and
pkg/cloudprovider/providers/fake. Real cloud SDK providers (aws, gce,
openstack, ...) are out of scope in a hermetic build; the interface +
fake is what the service/route controllers and cloud volumes program
against — the reference's own controllers are tested exactly this way.
"""

from .cloud import (CloudProvider, FakeCloudProvider, Instances,
                    LoadBalancer, LoadBalancers, Route, Routes, Zone,
                    Zones)

__all__ = ["CloudProvider", "FakeCloudProvider", "Instances",
           "LoadBalancer", "LoadBalancers", "Route", "Routes", "Zone",
           "Zones"]
