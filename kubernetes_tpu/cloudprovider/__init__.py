"""Cloud provider interface + fake + one wire-real provider.

Reference: pkg/cloudprovider/cloud.go (Interface: Instances,
LoadBalancers (TCPLoadBalancer at v1.1), Zones, Routes) and
pkg/cloudprovider/providers. `openstack.py` is a wire-real client of
the OpenStack API shapes (keystone/nova/neutron LBaaS v1), proven
against a mock cloud; aws/gce SDK integrations stay out of scope in a
hermetic build, with the interface + fake being what the service/route
controllers and cloud volumes program against — the reference's own
controllers are tested exactly this way.
"""

from .cloud import (CloudProvider, FakeCloudProvider, Instances,
                    LoadBalancer, LoadBalancers, Route, Routes, Zone,
                    Zones)

__all__ = ["CloudProvider", "FakeCloudProvider", "Instances",
           "LoadBalancer", "LoadBalancers", "Route", "Routes", "Zone",
           "Zones"]
