"""OpenStack cloud provider — a wire-real client of the OpenStack APIs.

Reference: pkg/cloudprovider/providers/openstack/openstack.go — the
provider is a CLIENT of keystone (v2 tokens + service catalog), nova
(servers, os-volume_attachments), and neutron LBaaS v1 (pools /
members / monitors / vips). This implementation speaks those same wire
shapes over HTTP so it runs against any endpoint that serves them —
in tests, a mock cloud (tests/test_openstack_provider.py), matching
how the daemon runtime proves the engine boundary. gophercloud's role
collapses into ~a page of urllib.

Surface parity with openstack.go:
  Instances:      List (servers by name filter :292), NodeAddresses
                  (:418 — accessIPv4/v6 then address pools), ExternalID
                  (:459 server id)
  TCPLoadBalancer: Get/Ensure/Update/Delete (:633-907 — pool per LB,
                  one member per host, vip carrying the external
                  address; LBaaS v1 semantics)
  Zones:          GetZone from config (:914 — av zone from config)
  AttachDisk/DetachDisk (:925,:961 — nova volume attachments)
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from .cloud import (CloudProvider, Instances, LoadBalancer, LoadBalancers,
                    Route, Routes, Zone, Zones)


class OpenStackError(RuntimeError):
    pass


class _Session:
    """Keystone v2 password auth -> token + service catalog endpoints
    (ref: openstack.go newOpenStack -> openstack.Authenticate)."""

    def __init__(self, auth_url: str, username: str, password: str,
                 tenant: str, timeout: float = 15.0, region: str = ""):
        self.auth_url = auth_url.rstrip("/")
        self.username = username
        self.password = password
        self.tenant = tenant
        self.timeout = timeout
        self.region = region
        self.token = ""
        self.endpoints: Dict[str, str] = {}  # service type -> public URL

    def authenticate(self) -> None:
        body = {"auth": {"passwordCredentials": {
            "username": self.username, "password": self.password},
            "tenantName": self.tenant}}
        data = self._raw_request("POST", self.auth_url + "/tokens", body,
                                 token=False)
        self._consume_access(data)

    def _consume_access(self, data) -> None:
        """Token + region-matched service catalog from a keystone v2
        access response — shared by every auth flavor (password here,
        RAX-KSKEY api key in rackspace.py)."""
        access = (data or {}).get("access", {})
        self.token = access.get("token", {}).get("id", "")
        if not self.token:
            raise OpenStackError("identity service returned no token")
        for svc in access.get("serviceCatalog", []):
            eps = svc.get("endpoints") or []
            if not eps:
                continue
            # region-matched endpoint first (the reference resolves by
            # configured region); fall back to the catalog's first
            chosen = next((e for e in eps
                           if not self.region
                           or e.get("region") == self.region), eps[0])
            self.endpoints[svc.get("type", "")] = \
                chosen.get("publicURL", "").rstrip("/")

    def endpoint(self, service_type: str) -> str:
        url = self.endpoints.get(service_type, "")
        if not url:
            raise OpenStackError(
                f"no {service_type!r} endpoint in the service catalog")
        return url

    def _raw_request(self, method: str, url: str,
                     body: Optional[dict] = None, token: bool = True):
        payload = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if token:
            headers["X-Auth-Token"] = self.token
        req = urllib.request.Request(url, data=payload, headers=headers,
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read()
                return json.loads(raw) if raw else None
        except urllib.error.HTTPError as e:
            if e.code == 404 and method in ("GET", "DELETE"):
                # absent resource: a read answers None, a delete is
                # idempotent; a 404 on POST (service not enabled, wrong
                # URL) must surface as a diagnosable error instead
                return None
            raise OpenStackError(
                f"{method} {url}: HTTP {e.code} "
                f"{e.read().decode(errors='replace')[:200]}")
        except (urllib.error.URLError, OSError) as e:
            raise OpenStackError(f"{method} {url}: {e}")

    def request(self, method: str, service: str, path: str,
                body: Optional[dict] = None):
        """One authenticated call, re-authenticating once on 401 (the
        token-expiry path gophercloud handles internally)."""
        url = self.endpoint(service) + path
        try:
            return self._raw_request(method, url, body)
        except OpenStackError as e:
            if "HTTP 401" not in str(e):
                raise
            self.authenticate()
            return self._raw_request(method, url, body)


class OpenStackInstances(Instances):
    def __init__(self, session: _Session):
        self._s = session

    def _servers(self, name_filter: str = "") -> List[dict]:
        q = f"?name={urllib.parse.quote(name_filter)}" if name_filter \
            else ""
        data = self._s.request("GET", "compute", f"/servers/detail{q}")
        return (data or {}).get("servers", [])

    def _server_by_name(self, name: str) -> dict:
        # server-side name filter (nova's is substring/regex; keep the
        # exact-match check client-side like the reference's ^name$)
        for srv in self._servers(name):
            if srv.get("name") == name:
                return srv
        raise KeyError(f"instance {name!r} not found")

    def list_instances(self, name_filter: str = "") -> List[str]:
        return [s.get("name", "") for s in self._servers(name_filter)]

    def node_addresses(self, name: str) -> List[str]:
        """(ref: openstack.go:418 — accessIPv4 first, then every pool
        in the addresses map)"""
        srv = self._server_by_name(name)
        out: List[str] = []
        if srv.get("accessIPv4"):
            out.append(srv["accessIPv4"])
        for _pool, addrs in (srv.get("addresses") or {}).items():
            for a in addrs:
                addr = a.get("addr")
                if addr and addr not in out:
                    out.append(addr)
        return out

    def external_id(self, name: str) -> str:
        return self._server_by_name(name).get("id", "")


class OpenStackLoadBalancers(LoadBalancers):
    """neutron LBaaS v1 (ref: openstack.go:633-907): one pool per LB,
    one member per host, a vip fronting the pool."""

    def __init__(self, session: _Session, subnet_id: str = "",
                 instances: "Optional[OpenStackInstances]" = None):
        self._s = session
        self.subnet_id = subnet_id
        # nova view for host-name <-> member-IP translation: members
        # take IPs (getAddressByName before members.Create,
        # openstack.go EnsureTCPLoadBalancer) while the service
        # controller speaks node names — get() must answer in the
        # controller's vocabulary or its host diff never converges
        self._instances = instances or OpenStackInstances(session)

    def _vip_by_name(self, name: str) -> Optional[dict]:
        data = self._s.request(
            "GET", "network", f"/lb/vips?name={urllib.parse.quote(name)}")
        vips = (data or {}).get("vips", [])
        return vips[0] if vips else None

    def _lb_of(self, vip: dict, region: str) -> LoadBalancer:
        """Fully-populated view: the service controller diffs
        lb.ports/lb.hosts against the desired state to decide whether
        to reconcile — empty fields would make every sync a rebuild."""
        name = vip.get("name", "")
        ports = [vip["protocol_port"]] if vip.get("protocol_port") else []
        hosts: List[str] = []
        pool = self._pool_for(name)
        if pool is not None:
            data = self._s.request(
                "GET", "network", f"/lb/members?pool_id={pool['id']}")
            hosts = self._names_of(
                [m.get("address", "")
                 for m in (data or {}).get("members", [])])
        return LoadBalancer(name=name, region=region,
                            external_ip=vip.get("address", ""),
                            ports=ports, hosts=hosts)

    def get(self, name: str, region: str) -> Optional[LoadBalancer]:
        vip = self._vip_by_name(name)
        if vip is None:
            return None
        return self._lb_of(vip, region)

    def list(self) -> List[LoadBalancer]:
        data = self._s.request("GET", "network", "/lb/vips")
        return [self._lb_of(v, "") for v in (data or {}).get("vips", [])]

    def ensure(self, name: str, region: str, ports: List[int],
               hosts: List[str],
               load_balancer_ip: str = "") -> LoadBalancer:
        """(ref: EnsureTCPLoadBalancer :653 — create pool, add a member
        per host, create the vip with the requested address when given;
        LBaaS v1 takes ONE port per vip, the
        reference rejects multi-port services :659)"""
        if len(ports) != 1:
            raise OpenStackError(
                "neutron LBaaS v1 supports exactly one port per "
                "load balancer (openstack.go:659)")
        existing = self.get(name, region)
        if existing is not None:
            self.update_hosts(name, region, hosts)
            return self.get(name, region) or existing
        pool = self._s.request("POST", "network", "/lb/pools", {
            "pool": {"name": name, "protocol": "TCP",
                     "subnet_id": self.subnet_id,
                     "lb_method": "ROUND_ROBIN"}})["pool"]
        for host in hosts:
            self._s.request("POST", "network", "/lb/members", {
                "member": {"pool_id": pool["id"],
                           "address": self._address_by_name(host),
                           "protocol_port": ports[0]}})
        try:
            vip = self._s.request("POST", "network", "/lb/vips", {
                "vip": {"name": name, "pool_id": pool["id"],
                        "protocol": "TCP", "protocol_port": ports[0],
                        **({"address": load_balancer_ip}
                           if load_balancer_ip else {}),
                        "subnet_id": self.subnet_id}})["vip"]
        except Exception:
            # existence is vip-keyed (get() looks the vip up): a failed
            # vip create must not strand the pool+members just made, or
            # every controller retry leaks another orphan pool into
            # neutron
            try:
                self._s.request("DELETE", "network",
                                f"/lb/pools/{pool['id']}")
            except OpenStackError:
                pass
            raise
        return LoadBalancer(name=name, region=region,
                            external_ip=vip.get("address", ""),
                            ports=list(ports), hosts=sorted(hosts))

    def _address_by_name(self, host: str) -> str:
        """Members take IP addresses, not node names: resolve each host
        through nova like the reference's getAddressByName
        (openstack.go EnsureTCPLoadBalancer resolves every host before
        members.Create). A host that is already an IP passes through."""
        import re as _re
        if _re.fullmatch(r"\d+\.\d+\.\d+\.\d+", host):
            return host
        addrs = self._instances.node_addresses(host)
        if not addrs:
            raise OpenStackError(f"no address found for host {host!r}")
        return addrs[0]

    def _names_of(self, addrs: List[str]) -> List[str]:
        """Reverse-translate member IPs to node names for the
        controller-facing host list; unknown IPs pass through."""
        ip_to_name = {}
        try:
            for srv in self._instances._servers():
                srv_name = srv.get("name", "")
                if srv.get("accessIPv4"):
                    ip_to_name.setdefault(srv["accessIPv4"], srv_name)
                for _pool, a in (srv.get("addresses") or {}).items():
                    for rec in a:
                        if rec.get("addr"):
                            ip_to_name.setdefault(rec["addr"], srv_name)
        except OpenStackError:
            pass
        return sorted(ip_to_name.get(a, a) for a in addrs)

    def _pool_for(self, name: str) -> Optional[dict]:
        data = self._s.request(
            "GET", "network",
            f"/lb/pools?name={urllib.parse.quote(name)}")
        pools = (data or {}).get("pools", [])
        return pools[0] if pools else None

    def update_hosts(self, name: str, region: str,
                     hosts: List[str]) -> None:
        """(ref: UpdateTCPLoadBalancer :780 — diff desired hosts against
        pool members; add the missing, delete the extra)"""
        pool = self._pool_for(name)
        if pool is None:
            raise OpenStackError(f"load balancer {name!r} not found")
        data = self._s.request(
            "GET", "network", f"/lb/members?pool_id={pool['id']}")
        members = (data or {}).get("members", [])
        have = {m.get("address"): m for m in members}
        # the LB's port lives on the vip (pools carry none in LBaaS
        # v1); a zero-member pool must still add members on the right
        # port
        vip = self._vip_by_name(name)
        port = (vip or {}).get("protocol_port") or (
            members[0].get("protocol_port") if members else 0)
        if not port:
            raise OpenStackError(
                f"load balancer {name!r} has no resolvable port")
        want = {self._address_by_name(h) for h in hosts}
        for addr in sorted(want - set(have)):
            self._s.request("POST", "network", "/lb/members", {
                "member": {"pool_id": pool["id"], "address": addr,
                           "protocol_port": port}})
        for addr, member in have.items():
            if addr not in want:
                self._s.request("DELETE", "network",
                                f"/lb/members/{member['id']}")

    def delete(self, name: str, region: str) -> None:
        """(ref: EnsureTCPLoadBalancerDeleted :841 — vip, then members,
        then pool)"""
        vip = self._vip_by_name(name)
        if vip is not None:
            self._s.request("DELETE", "network", f"/lb/vips/{vip['id']}")
        pool = self._pool_for(name)
        if pool is not None:
            data = self._s.request(
                "GET", "network", f"/lb/members?pool_id={pool['id']}")
            for member in (data or {}).get("members", []):
                self._s.request("DELETE", "network",
                                f"/lb/members/{member['id']}")
            self._s.request("DELETE", "network",
                            f"/lb/pools/{pool['id']}")


class OpenStackProvider(CloudProvider, Zones):
    """(ref: openstack.go OpenStack; ProviderName "openstack")"""

    name = "openstack"

    def __init__(self, auth_url: str, username: str, password: str,
                 tenant: str, region: str = "RegionOne",
                 availability_zone: str = "nova", subnet_id: str = ""):
        self._session = _Session(auth_url, username, password, tenant,
                                 region=region)
        self._session.authenticate()
        self.region = region
        self.availability_zone = availability_zone
        self._instances = OpenStackInstances(self._session)
        self._load_balancers = OpenStackLoadBalancers(
            self._session, subnet_id, instances=self._instances)

    def instances(self) -> Optional[Instances]:
        return self._instances

    def load_balancers(self) -> Optional[LoadBalancers]:
        return self._load_balancers

    def zones(self) -> Optional[Zones]:
        return self

    def get_zone(self) -> Zone:
        # ref: openstack.go:914 — zone comes from provider config
        return Zone(failure_domain=self.availability_zone,
                    region=self.region)

    def routes(self) -> Optional[Routes]:
        return None  # ref: openstack.go:920 Routes not supported

    # ------------------------------------------------ volume attachments

    def attach_disk(self, disk_name: str, node: str) -> None:
        """(ref: AttachDisk :925 — nova os-volume_attachments)"""
        server_id = self._instances.external_id(node)
        self._session.request(
            "POST", "compute",
            f"/servers/{server_id}/os-volume_attachments",
            {"volumeAttachment": {"volumeId": disk_name}})

    def detach_disk(self, disk_name: str, node: str) -> None:
        """(ref: DetachDisk :961)"""
        server_id = self._instances.external_id(node)
        self._session.request(
            "DELETE", "compute",
            f"/servers/{server_id}/os-volume_attachments/{disk_name}")
