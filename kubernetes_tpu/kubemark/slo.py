"""Density/latency SLOs over the hollow fleet.

Reference: the e2e suite enforces hard latency gates —
  - API calls: p99 < 1s   (test/e2e/metrics_util.go:41-47 apiCallLatency
    thresholds, :194-200 HighLatencyRequests gate)
  - Pod startup: p50 < 5s (test/e2e/metrics_util.go:224-225 +
    density.go:203-208, latency.go:172 — create -> Running observed by
    a watch)

This module measures both over the same kubemark harness the
throughput benchmark uses, but with the API surface served over REAL
HTTP (the reference measures the apiserver, not an in-proc shortcut):
pods are POSTed through the HTTP client, a prober thread issues
GET/LIST calls throughout the run, and a watch records when each pod
is first seen Running. check() applies the reference's gates.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api.client import HttpClient, InProcClient
from ..api.registry import Registry
from ..api.server import ApiServer
from ..core import types as api
from ..sched.batch import BatchScheduler
from ..sched.factory import ConfigFactory
from .benchmark import _bench_pod
from .fleet import HollowFleet

API_P99_LIMIT_S = 1.0      # ref: metrics_util.go:41-47
STARTUP_P50_LIMIT_S = 5.0  # ref: metrics_util.go:224-225, density.go:203


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


@dataclass
class SLOResult:
    n_nodes: int
    n_pods: int
    running: int
    elapsed_s: float
    api_p50_s: float
    api_p90_s: float
    api_p99_s: float
    api_calls: int
    startup_p50_s: float
    startup_p90_s: float
    startup_p99_s: float
    # bulk creates measured separately: one 256-pod batch POST is not
    # a representative per-request sample for the reference's API-call
    # latency gate (metrics_util.go measures standard verbs)
    batch_create_p99_s: float = 0.0
    batch_creates: int = 0
    api_p99_limit_s: float = API_P99_LIMIT_S
    startup_p50_limit_s: float = STARTUP_P50_LIMIT_S

    @property
    def api_ok(self) -> bool:
        return self.api_p99_s < self.api_p99_limit_s

    @property
    def startup_ok(self) -> bool:
        return self.startup_p50_s < self.startup_p50_limit_s

    def check(self) -> None:
        """Raise AssertionError when a gate is violated — the e2e
        suite's hard-failure semantics (density.go asserts, not logs)."""
        assert self.api_ok, (
            f"API p99 {self.api_p99_s:.3f}s exceeds "
            f"{self.api_p99_limit_s}s (ref metrics_util.go:194-200)")
        assert self.startup_ok, (
            f"pod startup p50 {self.startup_p50_s:.3f}s exceeds "
            f"{self.startup_p50_limit_s}s (ref density.go:203-208)")

    def as_dict(self) -> dict:
        return {
            "nodes": self.n_nodes, "pods": self.n_pods,
            "running": self.running,
            "elapsed_s": round(self.elapsed_s, 2),
            "api_p50_ms": round(self.api_p50_s * 1e3, 2),
            "api_p90_ms": round(self.api_p90_s * 1e3, 2),
            "api_p99_ms": round(self.api_p99_s * 1e3, 2),
            "api_calls": self.api_calls,
            "batch_create_p99_ms": round(self.batch_create_p99_s * 1e3,
                                         2),
            "batch_creates": self.batch_creates,
            "startup_p50_s": round(self.startup_p50_s, 3),
            "startup_p90_s": round(self.startup_p90_s, 3),
            "startup_p99_s": round(self.startup_p99_s, 3),
            "api_slo_ok": self.api_ok,
            "startup_slo_ok": self.startup_ok,
        }


def run_density_slo(n_nodes: int = 1000, n_pods: int = 3000,
                    timeout_s: float = 300.0,
                    max_pods_per_node: int = 40) -> SLOResult:
    """Stand up master-over-HTTP + hollow fleet + batch scheduler, blast
    pods, and measure the two SLO families until every pod is Running."""
    import sys
    sys.setswitchinterval(0.001)
    registry = Registry()
    server = ApiServer(registry, port=0).start()
    inproc = InProcClient(registry)
    http = HttpClient(server.url)

    api_lat: List[float] = []
    batch_lat: List[float] = []
    api_lock = threading.Lock()

    def timed(fn, *a, **kw):
        t0 = time.monotonic()
        out = fn(*a, **kw)
        with api_lock:
            api_lat.append(time.monotonic() - t0)
        return out

    # fleet + scheduler ride the in-proc path (separate processes in a
    # real deployment; the HTTP surface under measurement is the one
    # the pod writers and probers hit, as in the reference's density
    # run where the e2e client measures the apiserver)
    fleet = HollowFleet(inproc, n_nodes, cpu="4", memory="32Gi",
                        max_pods=max_pods_per_node,
                        heartbeat_interval=60.0).run()
    factory = ConfigFactory(inproc, rate_limit=False).start()
    sched = BatchScheduler(factory.create_batch()).run()

    created_at: Dict[str, float] = {}
    running_at: Dict[str, float] = {}
    all_running = threading.Event()
    watcher = registry.watch("pods", "default")

    def track_running():
        # independent of created_at: a Running confirm can race ahead
        # of the creating thread's bookkeeping, and a pod missed here
        # would stall the run to its timeout
        for ev in watcher:
            pod = ev.object
            name = pod.metadata.name
            if (name.startswith("bench-pod-") and name not in running_at
                    and ev.type != "DELETED"
                    and pod.status.phase == "Running"):
                running_at[name] = time.monotonic()
                if len(running_at) >= n_pods:
                    all_running.set()

    stop_probe = threading.Event()

    def prober():
        """Steady background API load, measured: the reference's gate
        covers every verb the cluster serves during density."""
        i = 0
        while not stop_probe.is_set():
            try:
                timed(http.list, "nodes")
                timed(http.get, "namespaces", "default")
                names = list(created_at)
                if names:
                    timed(http.get, "pods", names[i % len(names)])
                i += 1
            except Exception:
                pass  # a failed probe still counted its latency
            stop_probe.wait(0.02)

    deadline = time.time() + timeout_s
    try:
        while time.time() < deadline and \
                len(factory.node_lister.list()) < n_nodes:
            time.sleep(0.05)
        # warm the engine's compile cache at the run's real shapes (a
        # live scheduler has warm caches; XLA compiles inside the
        # measured window would bill ~seconds of compiler time to the
        # first pods' startup SLO)
        from .benchmark import _warmup_batch
        _warmup_batch(sched, factory)
        threading.Thread(target=track_running, daemon=True).start()
        threading.Thread(target=prober, daemon=True).start()

        start = time.monotonic()
        chunk = 128
        for base in range(0, n_pods, chunk):
            pods = [_bench_pod(i) for i in range(base,
                                                 min(base + chunk, n_pods))]
            # creation time = just BEFORE the POST (the reference
            # measures from pod creation, density.go), recorded first
            # so a fast Running confirm can never outrun it
            t0 = time.monotonic()
            for p in pods:
                created_at.setdefault(p.metadata.name, t0)
            http.create_batch("pods", pods, "default")
            batch_lat.append(time.monotonic() - t0)
        all_running.wait(timeout=max(0.0, deadline - time.time()))
        elapsed = time.monotonic() - start
    finally:
        stop_probe.set()
        watcher.stop()
        sched.stop()
        factory.stop()
        fleet.stop()
        server.stop()

    startups = sorted(running_at[n] - created_at[n]
                      for n in running_at if n in created_at)
    with api_lock:
        lats = sorted(api_lat)
    return SLOResult(
        n_nodes=n_nodes, n_pods=n_pods, running=len(running_at),
        elapsed_s=elapsed,
        api_p50_s=_percentile(lats, 0.50),
        api_p90_s=_percentile(lats, 0.90),
        api_p99_s=_percentile(lats, 0.99),
        api_calls=len(lats),
        startup_p50_s=_percentile(startups, 0.50),
        startup_p90_s=_percentile(startups, 0.90),
        startup_p99_s=_percentile(startups, 0.99),
        batch_create_p99_s=_percentile(sorted(batch_lat), 0.99),
        batch_creates=len(batch_lat))


def main() -> None:
    import argparse
    import json

    from ..utils.platform import ensure_live_platform
    ensure_live_platform()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--pods", type=int, default=3000)
    ap.add_argument("--no-check", action="store_true")
    args = ap.parse_args()
    r = run_density_slo(args.nodes, args.pods)
    print(json.dumps({"metric": "density_slo", **r.as_dict()}))
    if not args.no_check:
        r.check()


if __name__ == "__main__":
    main()
