"""Density/latency SLOs over the hollow fleet.

Reference: the e2e suite enforces hard latency gates —
  - API calls: p99 < 1s   (test/e2e/metrics_util.go:41-47 apiCallLatency
    thresholds, :194-200 HighLatencyRequests gate)
  - Pod startup: p50 < 5s (test/e2e/metrics_util.go:224-225 +
    density.go:203-208, latency.go:172 — create -> Running observed by
    a watch)

Measurement methodology (r4, after the r3 verdict voided a 6-sample
client-probe p99): API latency is read SERVER-SIDE from the
apiserver's own per-(verb, resource) service-time summaries — exactly
where the reference's gate reads (HighLatencyRequests walks apiserver
metrics, metrics_util.go:194-200) — so a GIL-starved client thread can
no longer shrink the sample set; every request the server handled is a
sample. Prober threads still run to put realistic read load on the
server during the window (the reference density run measures a loaded
apiserver), but their clocks are not the measurement. A percentile
claim is marked valid only at >= MIN_API_SAMPLES; the density matrix
(3 and 30 pods/node, density.go:203-208) is driven by bench.py running
this twice.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api.client import HttpClient, InProcClient
from ..api.registry import Registry
from ..api.server import ApiServer
from ..core import types as api
from ..obs.metricsplane import SLODef
from ..sched.batch import BatchScheduler
from ..sched.factory import ConfigFactory
from ..utils.metrics import (APISERVER_LATENCY_SUMMARY, CROWD_COUNTERS,
                             SURGE_COUNTERS, WATCH_LAG_HISTOGRAM,
                             MetricsRegistry)
from .benchmark import _bench_pod
from .fleet import HollowFleet

API_P99_LIMIT_S = 1.0      # ref: metrics_util.go:41-47
STARTUP_P50_LIMIT_S = 5.0  # ref: metrics_util.go:224-225, density.go:203
MIN_API_SAMPLES = 1000     # below this a percentile claim is void
MIN_ENDPOINT_SAMPLES = 10  # endpoints with fewer samples aren't gated

#: the metric-pinning lint contract: this module reads the spelling
#: pinned in utils/metrics.py, never a local literal
LATENCY_METRIC = APISERVER_LATENCY_SUMMARY

# ---------------------------------------------------- burn-rate SLOs
#
# Continuous SLOs the burn-rate evaluator (obs/metricsplane.py) runs
# over the fleet time-series, next to the end-of-run gates above.
# Windows are in SAMPLES (the soak scrapes once per workload tick),
# thresholds follow the SRE-workbook multi-window shape: TRIP needs
# the fast AND slow window burning, CLEAR needs only the fast window
# calm — so a flash crowd trips within one tick of landing and clears
# within a bounded tick lag once binds drain.

#: flash-crowd drain: of the crowd pods created, what fraction is
#: bound? The crowd injection itself drives the error ratio to ~1 at
#: the burst tick (pods cannot bind in the same tick they land), so
#: this alert's trip/clear ticks ARE the crowd timeline — replayable,
#: and gated by the workload soak.
CROWD_BIND_SLO = SLODef(
    name="crowd-bind-availability",
    metric=CROWD_COUNTERS[0],        # crowd_pods_created_total
    good_metric=CROWD_COUNTERS[1],   # crowd_pods_bound_total
    kind="ratio",
    objective=0.999,
    fast_window=2, slow_window=8,
    fast_burn=10.0, slow_burn=2.0)

#: apiserver service time against the reference's 1s p99 limit, read
#: from the merged fleet histogram: "good" = requests <= 1s (1e6 us
#: is a pinned bucket bound, so the count is exact, no interpolation)
API_LATENCY_SLO = SLODef(
    name="api-latency-1s",
    metric=APISERVER_LATENCY_SUMMARY,
    kind="histogram_le",
    threshold_le=1.0e6,              # us — ref metrics_util.go:41-47
    objective=0.99,
    fast_window=2, slow_window=8,
    fast_burn=10.0, slow_burn=2.0)

#: watch delivery: publish-ring enqueue -> watcher fan-out, gated at
#: p99-style "good = delivered within 250ms" (0.25 is a pinned
#: WATCH_LAG bucket bound, so the good count is exact). The fan-out
#: soak trips this when a worker shard falls behind its partition
#: under the 10k-watcher create storm; the steady-state soaks burn ~0
#: (delivery is sub-ms when fan-out keeps up). Histogram label sets
#: are summed, so the default shard's unlabeled observations and the
#: workers' {shard=...} observations gate together.
WATCH_DELIVER_SLO = SLODef(
    name="watch-deliver-250ms",
    metric=WATCH_LAG_HISTOGRAM,
    kind="histogram_le",
    threshold_le=0.25,               # s — pinned bucket bound
    objective=0.99,
    fast_window=2, slow_window=8,
    fast_burn=10.0, slow_burn=2.0)

#: surge bind under preemption: of the high-priority surge pods
#: created, what fraction bound within the fast-bind limit (the
#: SURGE_BIND_HISTOGRAM 5s bucket edge)? Same timeline semantics as
#: CROWD_BIND_SLO — the surge injection drives the ratio to ~1 at the
#: surge tick (victims must drain first), so trip/clear ARE the
#: flash-drain timeline; in soaks that never inject a surge both
#: counters stay 0 and the burn is 0 (never trips).
SURGE_BIND_SLO = SLODef(
    name="surge-bind-availability",
    metric=SURGE_COUNTERS[0],        # surge_pods_created_total
    good_metric=SURGE_COUNTERS[1],   # surge_pods_bound_fast_total
    kind="ratio",
    objective=0.999,
    fast_window=2, slow_window=8,
    fast_burn=10.0, slow_burn=2.0)

#: the pinned fleet SLO set the soaks evaluate every sample
FLEET_SLOS = (CROWD_BIND_SLO, API_LATENCY_SLO, WATCH_DELIVER_SLO,
              SURGE_BIND_SLO)


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


@dataclass
class SLOResult:
    n_nodes: int
    n_pods: int
    running: int
    elapsed_s: float
    # all-traffic percentiles over the server-side sample windows
    api_p50_s: float
    api_p90_s: float
    api_p99_s: float
    api_calls: int            # total requests the server recorded
    startup_p50_s: float
    startup_p90_s: float
    startup_p99_s: float
    # per-(verb, resource) server-side stats: "GET pods" -> {count,
    # p50_ms, p90_ms, p99_ms} — the reference's HighLatencyRequests view
    api_verbs: Dict[str, dict] = field(default_factory=dict)
    api_p99_limit_s: float = API_P99_LIMIT_S
    startup_p50_limit_s: float = STARTUP_P50_LIMIT_S

    @property
    def api_samples_valid(self) -> bool:
        return self.api_calls >= MIN_API_SAMPLES

    @property
    def api_ok(self) -> Optional[bool]:
        """The reference gate: NO (verb, resource) endpoint with a
        meaningful sample count runs p99 over the limit
        (metrics_util.go:194-200 counts violations per endpoint).
        ':batch' endpoints are reported but not gated — one 128-pod
        batch POST is not a representative single-request sample
        (the server labels them out, api/server.py).

        COUPLED to the sample floor (the r3/r4 lesson, finally wired
        in): a starved window returns None — a percentile gate that
        'passed' on too few samples proves nothing and must never
        read true."""
        if not self.api_samples_valid:
            return None
        return self._api_gate()

    def _api_gate(self) -> bool:
        """The latency comparison alone, no sample-floor coupling —
        check() applies its own (possibly relaxed) floor first."""
        worst = max((v["p99_ms"] for k, v in self.api_verbs.items()
                     if v["count"] >= MIN_ENDPOINT_SAMPLES
                     and not k.endswith(":batch")),
                    default=self.api_p99_s * 1e3)
        return worst < self.api_p99_limit_s * 1e3

    @property
    def startup_ok(self) -> bool:
        return self.startup_p50_s < self.startup_p50_limit_s

    def check(self, min_samples: int = MIN_API_SAMPLES) -> None:
        """Raise AssertionError when a gate is violated — the e2e
        suite's hard-failure semantics (density.go asserts, not logs).
        An invalid sample count is itself a failure: a gate that
        passed on too few samples proves nothing (the r3 verdict's
        6-sample p99). min_samples is relaxable ONLY for scaled-down
        CI fixtures; bench artifacts use the full floor."""
        assert self.api_calls >= min_samples, (
            f"API latency gate saw only {self.api_calls} samples "
            f"(need {min_samples})")
        assert self._api_gate(), (
            f"an API endpoint's p99 exceeds {self.api_p99_limit_s}s: "
            + str({k: v for k, v in self.api_verbs.items()
                   if v['p99_ms'] >= self.api_p99_limit_s * 1e3}))
        assert self.startup_ok, (
            f"pod startup p50 {self.startup_p50_s:.3f}s exceeds "
            f"{self.startup_p50_limit_s}s (ref density.go:203-208)")

    def as_dict(self) -> dict:
        return {
            "nodes": self.n_nodes, "pods": self.n_pods,
            "pods_per_node": round(self.n_pods / max(1, self.n_nodes), 1),
            "running": self.running,
            "elapsed_s": round(self.elapsed_s, 2),
            "api_p50_ms": round(self.api_p50_s * 1e3, 2),
            "api_p90_ms": round(self.api_p90_s * 1e3, 2),
            "api_p99_ms": round(self.api_p99_s * 1e3, 2),
            "api_calls": self.api_calls,
            "api_samples_valid": self.api_samples_valid,
            "api_source": "server-side summaries",
            "api_verbs": self.api_verbs,
            "startup_p50_s": round(self.startup_p50_s, 3),
            "startup_p90_s": round(self.startup_p90_s, 3),
            "startup_p99_s": round(self.startup_p99_s, 3),
            "api_slo_ok": self.api_ok,
            "startup_slo_ok": self.startup_ok,
        }


def run_density_slo(n_nodes: int = 1000, n_pods: int = 3000,
                    timeout_s: float = 600.0,
                    max_pods_per_node: int = 40,
                    node_cpu: str = "4") -> SLOResult:
    """Stand up master-over-HTTP + hollow fleet + batch scheduler, blast
    pods, and measure the two SLO families until every pod is Running.
    node_cpu scales the hollow nodes for the high density tiers (100
    bench pods x 100m does not fit a 4-CPU node; the reference's
    50/100-pods-per-node tiers run on clusters sized for them,
    density.go:203-208)."""
    # a LATENCY benchmark wants short GIL slices: with ~40 runnable
    # threads at the throughput-tuned 5ms interval, one API request can
    # queue behind 200ms+ of scheduler/binder slices — the GET-nodes
    # p99 tail at 5k density was exactly that. 1ms trades a little
    # throughput for request-latency fairness (the reference's
    # apiserver is its own OS-scheduled process; this is the in-proc
    # analogue).
    import sys as _sys
    _prev_si = _sys.getswitchinterval()
    _sys.setswitchinterval(0.001)
    registry = Registry()
    metrics = MetricsRegistry()   # per-run registry: no cross-run mixing
    server = ApiServer(registry, port=0, metrics=metrics).start()
    inproc = InProcClient(registry)
    http = HttpClient(server.url)

    # fleet + scheduler ride the in-proc path (separate processes in a
    # real deployment; the HTTP surface under measurement is the one
    # the pod writers and probers hit, as in the reference's density
    # run where the e2e client measures the apiserver)
    fleet = HollowFleet(inproc, n_nodes, cpu=node_cpu, memory="32Gi",
                        max_pods=max_pods_per_node,
                        heartbeat_interval=60.0).run()
    factory = ConfigFactory(inproc, rate_limit=False).start()
    sched = BatchScheduler(factory.create_batch()).run()

    created_at: Dict[str, float] = {}
    running_at: Dict[str, float] = {}
    all_running = threading.Event()
    watcher = registry.watch("pods", "default")

    def track_running():
        # independent of created_at: a Running confirm can race ahead
        # of the creating thread's bookkeeping, and a pod missed here
        # would stall the run to its timeout
        for ev in watcher:
            pod = ev.object
            name = pod.metadata.name
            if (name.startswith("bench-pod-") and name not in running_at
                    and ev.type != "DELETED"
                    and pod.status.phase == "Running"):
                running_at[name] = time.monotonic()
                if len(running_at) >= n_pods:
                    all_running.set()

    stop_probe = threading.Event()

    def prober(kind: str, cadence: float):
        """Background API read load (unmeasured client-side — the
        server records every request it serves)."""
        i = 0
        while not stop_probe.is_set():
            try:
                if kind == "get-pod":
                    names = list(created_at)
                    if names:
                        http.get("pods", names[i % len(names)])
                    else:
                        http.get("namespaces", "default")
                elif kind == "list-nodes":
                    http.list("nodes")
                else:
                    http.get("namespaces", "default")
                i += 1
            except Exception:
                pass
            stop_probe.wait(cadence)

    probers = [threading.Thread(target=prober, args=(k, c), daemon=True)
               for k, c in (("get-pod", 0.01), ("get-pod", 0.01),
                            ("get-ns", 0.02), ("list-nodes", 0.15))]

    deadline = time.time() + timeout_s
    try:
        while time.time() < deadline and \
                len(factory.node_lister.list()) < n_nodes:
            time.sleep(0.05)
        # warm the engine's compile cache at the run's real shapes (a
        # live scheduler has warm caches; XLA compiles inside the
        # measured window would bill ~seconds of compiler time to the
        # first pods' startup SLO)
        from .benchmark import _warmup_batch
        _warmup_batch(sched, factory)
        # one pre-window nodes LIST: the reference's density run also
        # starts against a warmed master — its framework lists nodes
        # repeatedly while waiting for them to register (density.go
        # WaitForNodes), so the boot-time cold encode of the whole
        # fleet never lands inside the measured phase there either
        try:
            http.list("nodes")
        except Exception:
            pass
        threading.Thread(target=track_running, daemon=True).start()
        for t in probers:
            t.start()

        start = time.monotonic()
        chunk = 128
        for base in range(0, n_pods, chunk):
            pods = [_bench_pod(i) for i in range(base,
                                                 min(base + chunk, n_pods))]
            # creation time = just BEFORE the POST (the reference
            # measures from pod creation, density.go), recorded first
            # so a fast Running confirm can never outrun it
            t0 = time.monotonic()
            for p in pods:
                created_at.setdefault(p.metadata.name, t0)
            http.create_batch("pods", pods, "default")
        all_running.wait(timeout=max(0.0, deadline - time.time()))
        elapsed = time.monotonic() - start
    finally:
        # restore the caller's GIL slice: this knob is process-wide and
        # bench.py measures throughput after the SLO sweep
        _sys.setswitchinterval(_prev_si)
        stop_probe.set()
        watcher.stop()
        sched.stop()
        factory.stop()
        fleet.stop()
        server.stop()

    startups = sorted(running_at[n] - created_at[n]
                      for n in running_at if n in created_at)

    # ---- server-side API latency read-out (us -> s) ----
    # ':batch' endpoints are REPORTED in api_verbs but excluded from
    # both the gate (api_ok) and the merged all-traffic percentiles —
    # one 128-pod batch POST is not a single-request sample, and the
    # merged number doubles as api_ok's fallback when no endpoint
    # reaches the per-endpoint sample floor
    verb_stats: Dict[str, dict] = {}
    merged: List[float] = []
    for labels, stats in metrics.summary_stats(LATENCY_METRIC).items():
        ld = dict(labels)
        key = f"{ld.get('verb', '?')} {ld.get('resource', '?')}"
        verb_stats[key] = {
            "count": stats["count"],
            "p50_ms": round(stats["p50"] / 1e3, 2),
            "p90_ms": round(stats["p90"] / 1e3, 2),
            "p99_ms": round(stats["p99"] / 1e3, 2)}
    for labels, samples in metrics.summary_samples(
            LATENCY_METRIC).items():
        if dict(labels).get("resource", "").endswith(":batch"):
            continue
        merged.extend(samples)
    merged.sort()
    total_calls = sum(v["count"] for k, v in verb_stats.items()
                      if not k.endswith(":batch"))

    return SLOResult(
        n_nodes=n_nodes, n_pods=n_pods, running=len(running_at),
        elapsed_s=elapsed,
        api_p50_s=_percentile(merged, 0.50) / 1e6,
        api_p90_s=_percentile(merged, 0.90) / 1e6,
        api_p99_s=_percentile(merged, 0.99) / 1e6,
        api_calls=total_calls,
        api_verbs=verb_stats,
        startup_p50_s=_percentile(startups, 0.50),
        startup_p90_s=_percentile(startups, 0.90),
        startup_p99_s=_percentile(startups, 0.99))


def main() -> None:
    import argparse
    import json

    from ..utils.platform import ensure_live_platform
    ensure_live_platform()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--pods", type=int, default=3000)
    ap.add_argument("--no-check", action="store_true")
    args = ap.parse_args()
    r = run_density_slo(args.nodes, args.pods)
    print(json.dumps({"metric": "density_slo", **r.as_dict()}))
    if not args.no_check:
        r.check()


if __name__ == "__main__":
    main()
