"""Kubemark: master-plane scale testing with hollow resources.

Reference: pkg/kubemark (HollowKubelet hollow_kubelet.go:35-80), deployed
by test/kubemark/start-kubemark.sh as NUM_NODES pods of real kubelet code
wired to fakes. Here the same idea runs in-process: agents.HollowKubelet
is the faithful per-node agent (own informer/heartbeat threads); for
thousand-node fleets HollowFleet multiplexes every node through ONE watch
stream and ONE status pump — the master sees the identical API traffic
(N node objects heartbeating, pods confirmed Running) without N x 3
threads.
"""

from .fleet import HollowFleet
from .benchmark import BenchmarkResult, run_scheduling_benchmark

__all__ = ["HollowFleet", "BenchmarkResult", "run_scheduling_benchmark"]
