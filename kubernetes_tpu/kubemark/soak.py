"""Soak: a steady-state control plane under continuous pod churn, with
leak gates.

Reference: test/soak/ (cauldron/serve_hostnames run clusters for hours
and fail on drift). Nothing in this repo ran the control plane longer
than a bench window before r4 — watcher lists, modeler tombstones,
event TTLs and RSS were reasoned about, never demonstrated. This
harness runs the full in-proc stack (registry + hollow fleet + batch
scheduler) while a churner creates, confirms and deletes pods at a
modest rate, sampling the leak-prone state on a cadence:

  - RSS (VmRSS from /proc/self/status)
  - store watcher-list length (dead watchers must be swept)
  - store key count (deleted pods must not accrete)
  - modeler assumed-pod + forget-tombstone counts (TTL'd)
  - live thread count (per-connection/per-pod threads must exit)

check() applies relative-drift gates between the warm baseline (taken
after the first churn cycle, so steady-state allocations don't count
as leaks) and the final sample.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api.client import InProcClient
from ..api.registry import Registry
from ..sched.batch import BatchScheduler
from ..sched.factory import ConfigFactory
from .benchmark import _bench_pod
from .fleet import HollowFleet

RSS_GROWTH_LIMIT = 0.35      # fraction over the warm baseline
THREAD_GROWTH_LIMIT = 8      # absolute extra threads tolerated
KEY_GROWTH_LIMIT = 50        # store keys beyond the warm baseline


def self_warm(store, t0: float, duration_s: float) -> bool:
    """The RSS baseline is valid once the store's watch-history deque
    has filled to its designed bound (its memory is budget, not leak);
    cap the wait at 40% of the run so a slow churner still leaves a
    measurement window."""
    with store._lock:
        full = len(store._history) == store._history.maxlen
    return full or (time.time() - t0) > 0.4 * duration_s


def _rss_kb() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


@dataclass
class SoakResult:
    duration_s: float
    cycles: int
    pods_churned: int
    samples: List[Dict[str, float]] = field(default_factory=list)

    @property
    def baseline(self) -> Dict[str, float]:
        return self.samples[0] if self.samples else {}

    @property
    def final(self) -> Dict[str, float]:
        return self.samples[-1] if self.samples else {}

    def check(self) -> None:
        """Hard leak gates (the soak suite's contract: drift IS
        failure). RSS/watchers/threads/keys gate start-vs-end against
        the warm baseline; tombstones are TTL-bounded BY DESIGN at
        churn_rate x TTL (measured ~26k oscillating at ~850 pods/s),
        so their gate is plateau-shaped: the second half of the run
        must not exceed the first half's peak by more than noise —
        monotonic growth means the TTL GC died."""
        b, f = self.baseline, self.final
        assert len(self.samples) >= 2, (
            "the sampler never produced a distinct baseline and final "
            "sample — the run measured nothing (sampler start is gated "
            "on self_warm; a stalled churner can skip it)")
        assert f["rss_kb"] <= b["rss_kb"] * (1 + RSS_GROWTH_LIMIT), (
            f"RSS grew {b['rss_kb']}kB -> {f['rss_kb']}kB "
            f"(> {RSS_GROWTH_LIMIT:.0%} over baseline)")
        assert f["watchers"] <= b["watchers"], (
            f"store watcher list grew {b['watchers']} -> "
            f"{f['watchers']} (dead watchers not swept)")
        assert f["threads"] <= b["threads"] + THREAD_GROWTH_LIMIT, (
            f"thread count grew {b['threads']} -> {f['threads']}")
        assert f["store_keys"] <= b["store_keys"] + KEY_GROWTH_LIMIT, (
            f"store keys grew {b['store_keys']} -> {f['store_keys']} "
            f"(deleted pods accreting?)")
        if "ledger" in f:
            assert f["ledger"] <= b.get("ledger", 0) + KEY_GROWTH_LIMIT, (
                f"incremental-encoder ledger grew {b.get('ledger')} -> "
                f"{f['ledger']} (deleted pods not removed from the "
                f"device state)")
            assert f.get("ledger_unknown_node", 0) <= \
                b.get("ledger_unknown_node", 0) + KEY_GROWTH_LIMIT, (
                "unknown-node bucket accreting")
        mid = len(self.samples) // 2
        first_peak = max(s["tombstones"] for s in self.samples[:mid + 1])
        second_peak = max(s["tombstones"] for s in self.samples[mid:])
        assert second_peak <= first_peak * 1.5 + 500, (
            f"modeler tombstones kept growing: first-half peak "
            f"{first_peak}, second-half peak {second_peak} "
            f"(TTL GC not running?)")

    def as_dict(self) -> dict:
        return {"duration_s": round(self.duration_s, 1),
                "cycles": self.cycles,
                "pods_churned": self.pods_churned,
                "baseline": self.baseline, "final": self.final,
                "n_samples": len(self.samples)}


def run_soak(duration_s: float = 600.0, n_nodes: int = 200,
             pods_per_cycle: int = 200,
             sample_every_s: float = 5.0,
             history_window: Optional[int] = None) -> SoakResult:
    """Churn cycles until the clock runs out: create a pod wave, wait
    until every pod is bound AND confirmed Running, delete the wave,
    wait until the store forgets it. Leak state is sampled throughout;
    the first sample is taken AFTER one full cycle (warm baseline).

    history_window: the store's watch window retains up to that many
    events BY DESIGN (~135MB at the default 100k with pod-sized
    objects) — short CI runs pass a small window so the by-design
    fill finishes before the baseline and the RSS gate measures
    leaks, not the window budget."""
    from ..core.store import Store
    registry = (Registry() if history_window is None
                else Registry(store=Store(window=history_window)))
    client = InProcClient(registry)
    fleet = HollowFleet(client, n_nodes, cpu="4", memory="32Gi",
                        max_pods=40, heartbeat_interval=30.0).run()
    factory = ConfigFactory(client, rate_limit=False).start()
    sched = BatchScheduler(factory.create_batch()).run()
    store = registry.store
    modeler = factory.modeler

    samples: List[Dict[str, float]] = []

    def sample() -> None:
        with store._lock:
            watchers = len(store._watchers)
            keys = len(store._data)
        with modeler._lock:
            tombs = len(modeler._forgotten)
            assumed = len(modeler._assumed._items)
        inc = sched._inc
        if inc is not None:
            with inc._lock:
                ledger = len(inc.pods)
                unknown = sum(len(v) for v in
                              inc.unknown_node_pods.values())
        else:
            ledger = unknown = 0
        samples.append({
            "t": round(time.time() - t0, 1),
            "rss_kb": _rss_kb(),
            "watchers": watchers,
            "store_keys": keys,
            "tombstones": tombs,
            "assumed": assumed,
            "ledger": ledger,
            "ledger_unknown_node": unknown,
            "threads": threading.active_count()})

    def wait_until(cond, timeout_s: float = 120.0) -> bool:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if cond():
                return True
            time.sleep(0.1)
        return False

    t0 = time.time()
    cycles = 0
    churned = 0
    stop_sampler = threading.Event()

    def sampler():
        while not stop_sampler.wait(sample_every_s):
            sample()

    try:
        assert wait_until(
            lambda: len(factory.node_lister.list()) >= n_nodes), \
            "fleet never registered"
        from .benchmark import _warmup_batch
        _warmup_batch(sched, factory)

        deadline = t0 + duration_s
        sampler_started = False
        while time.time() < deadline:
            base = cycles * pods_per_cycle
            names = [f"bench-pod-{base + i:06d}"
                     for i in range(pods_per_cycle)]
            # columnar create: the production writers' path (template
            # + name rows) soaks too, not just object-per-pod creates
            client.create_from_template("pods", _bench_pod(0), names,
                                        "default")

            def all_running():
                pods, _ = registry.list("pods", "default")
                running = {p.metadata.name for p in pods
                           if p.status.phase == "Running"}
                return all(n in running for n in names)

            assert wait_until(all_running), \
                f"cycle {cycles}: pods never all Running"
            for n in names:
                client.delete("pods", n, "default")

            def all_gone():
                pods, _ = registry.list("pods", "default")
                live = {p.metadata.name for p in pods}
                return not any(n in live for n in names)

            assert wait_until(all_gone), \
                f"cycle {cycles}: deleted pods still present"
            cycles += 1
            churned += pods_per_cycle
            if not sampler_started and self_warm(store, t0, duration_s):
                # warm baseline: caches, thread pools, compile
                # artifacts AND the watch-history window (which
                # retains its maxlen events by design — ~135MB at the
                # default 100k) all exist — growth from HERE is leak,
                # not budgeted fill
                sample()
                threading.Thread(target=sampler, daemon=True).start()
                sampler_started = True
        # all_gone above proved the DELETEs committed to the STORE,
        # but the scheduler's incremental encoder drains them from its
        # own watch stream — on a loaded box that drain can trail the
        # final sample and read as ledger growth. Settle it (bounded):
        # a genuine leak never drains and still fails the gate.
        inc = sched._inc
        if inc is not None and cycles:

            def ledger_drained():
                with inc._lock:
                    return not any(n in inc.pods for n in names)

            wait_until(ledger_drained, timeout_s=15.0)
        sample()  # final
    finally:
        stop_sampler.set()
        sched.stop()
        factory.stop()
        fleet.stop()

    return SoakResult(duration_s=time.time() - t0, cycles=cycles,
                      pods_churned=churned, samples=samples)


def main() -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--nodes", type=int, default=200)
    ap.add_argument("--pods-per-cycle", type=int, default=200)
    ap.add_argument("--no-check", action="store_true")
    ap.add_argument("--out", default="",
                    help="write the result JSON to this file as well")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU platform before jax init "
                         "(round-over-round comparable artifacts; "
                         "JAX_PLATFORMS alone is overridden by the "
                         "image's sitecustomize)")
    args = ap.parse_args()

    if args.cpu:
        from ..utils.platform import pin_cpu
        platform = pin_cpu()
    else:
        # probe-or-fallback BEFORE any jax touch: a wedged tunnel must
        # degrade the soak to the CPU platform, not kill it at import
        # (the same ensure_live_platform every bench entry uses)
        from ..utils.platform import ensure_live_platform
        platform, _probe = ensure_live_platform()
    r = run_soak(args.minutes * 60.0, args.nodes, args.pods_per_cycle)
    doc = {"metric": "soak", "platform": platform, "nodes": args.nodes,
           "pods_per_cycle": args.pods_per_cycle, **r.as_dict()}
    try:
        r.check()
        doc["gates"] = {"ok": True}
    except AssertionError as e:
        doc["gates"] = {"ok": False, "reason": str(e)}
    # the artifact records failures too — a failed round must not
    # leave the previous round's ok:true on disk
    if args.out:
        from .tpu_evidence import _atomic_write_json
        _atomic_write_json(args.out, doc)
    print(json.dumps(doc))
    if not args.no_check and not doc["gates"]["ok"]:
        raise SystemExit(f"soak gate failed: {doc['gates']['reason']}")


if __name__ == "__main__":
    main()
