"""Node-kill soak: the fleet-scale node-failure acceptance harness.

Stands up the full in-proc stack — registry, hollow fleet, batch
scheduler, replication manager, node-lifecycle controller — with every
component client wrapped in the seeded API-fault injector, runs an RC
to steady state, then hard-kills a seeded fraction of the fleet
mid-run (chaos.NodeFaultPlan -> HollowFleet.kill_nodes) and measures
recovery:

  kill -> stale heartbeats -> NodeController marks Unknown -> the
  scheduler's sched_ok mask retires the nodes -> uid-preconditioned
  eviction drains their pods -> the RC recreates -> the scheduler
  rebinds onto live nodes -> the fleet confirms Running.

Convergence gates (the ISSUE-5 acceptance bar): every RC replica
Running on a LIVE node, zero pods anywhere still bound to a dead node,
and the applied kill set equal to the plan's pure replay (same seed ->
identical schedule). Shared verbatim by the pytest soak
(tests/test_chaos.py) and the bench arm (bench.py
--node-kill-fraction), so the number the artifact records is exactly
the invariant the test enforces.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from ..api.client import InProcClient
from ..api.registry import Registry
from ..chaos import ChaosClient, FaultPlan, NodeChaos, NodeFaultPlan
from ..controllers.node import NodeController
from ..controllers.replication import ReplicationManager
from ..core import types as api
from ..core.errors import AlreadyExists
from ..utils.clock import REAL, Clock
from ..sched.batch import BatchScheduler
from ..sched.factory import ConfigFactory
from .benchmark import _bench_pod
from .fleet import HollowFleet


@dataclass
class NodeKillResult:
    converged: bool
    n_nodes: int
    replicas: int
    killed: List[str] = field(default_factory=list)
    #: seconds from RC creation to the kill
    kill_at_s: float = 0.0
    #: seconds from the kill to convergence (the recovery time)
    converge_s: float = 0.0
    #: pods the NodeController deleted off dead nodes
    evictions: int = 0
    #: bindings committed after the kill (replacement placements)
    rebinds: int = 0
    #: pods still bound to dead nodes at quiesce (gate: 0)
    dead_bound: int = 0
    #: times the partition valve engaged during the run (expected 0 for
    #: a sub-threshold kill; the partition gate drives it explicitly)
    partition_halts: int = 0
    #: the applied kill set equals the plan's pure replay
    schedule_replayed: bool = True
    #: why convergence failed, for the assertion message
    detail: str = ""

    def as_dict(self) -> Dict:
        return asdict(self)


def run_node_kill_soak(n_nodes: int = 40, replicas: int = 30,
                       kill_fraction: float = 0.10, seed: int = 0,
                       fault_rate: float = 0.05,
                       timeout: float = 120.0,
                       heartbeat_interval: float = 0.5,
                       monitor_period: float = 0.1,
                       monitor_grace_period: float = 1.5,
                       pod_eviction_timeout: float = 0.3,
                       registry: Optional[Registry] = None,
                       clock: Optional[Clock] = None
                       ) -> NodeKillResult:
    """One seeded node-kill soak; see the module docstring for the
    scenario. Timing knobs default to soak-compressed values (the
    production defaults would make recovery a 5+ minute wait)."""
    clock = clock or REAL
    registry = registry or Registry()
    plan = FaultPlan(seed=seed, error_rate=fault_rate)
    client = ChaosClient(InProcClient(registry), plan)
    node_plan = NodeFaultPlan(seed=seed, kill_fraction=kill_fraction)

    fleet = HollowFleet(client, n_nodes,
                        heartbeat_interval=heartbeat_interval,
                        jitter_seed=seed).run()
    factory = ConfigFactory(client, rate_limit=False).start()
    sched = BatchScheduler(factory.create_batch()).run()
    rc_mgr = ReplicationManager(client).run()
    # eviction limiter opened up: the soak's compressed timings would
    # otherwise spend minutes draining at the production 0.1 qps
    node_ctl = NodeController(
        client, monitor_period=monitor_period,
        monitor_grace_period=monitor_grace_period,
        pod_eviction_timeout=pod_eviction_timeout,
        eviction_qps=1000.0, eviction_burst=1000).run()
    chaos_nodes = NodeChaos(fleet, node_plan)
    result = NodeKillResult(converged=False, n_nodes=n_nodes,
                            replicas=replicas)

    # rebind counter rides the scheduler's own scheduled-pod informer
    # (one ADDED per committed binding — the reflector's field selector
    # admits a pod only once it is bound)
    post_kill = {"armed": False, "count": 0}

    def count_rebind(pod):
        if post_kill["armed"] and pod.spec.node_name:
            post_kill["count"] += 1

    factory.scheduled_observers.append(count_rebind)

    def wait_until(cond, deadline):
        while clock.monotonic() < deadline:
            if cond():
                return True
            clock.sleep(0.05)
        return cond()

    try:
        deadline = clock.monotonic() + timeout
        if not wait_until(
                lambda: len(factory.node_lister.list()) >= n_nodes,
                deadline):
            result.detail = "fleet never registered"
            return result

        rc = api.ReplicationController(
            metadata=api.ObjectMeta(name="nodekill", namespace="default"),
            spec=api.ReplicationControllerSpec(
                replicas=replicas, selector={"app": "nodekill"},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"app": "nodekill"}),
                    spec=_bench_pod(0).spec)))
        t0 = clock.monotonic()
        while True:  # RC creation rides the fault injector too
            try:
                client.create("replicationcontrollers", rc)
                break
            except AlreadyExists:
                break  # a replayed create already committed the RC
            except Exception:
                if clock.monotonic() > deadline:
                    result.detail = "rc create never landed"
                    return result
                clock.sleep(0.05)

        def live_pods():
            pods, _ = registry.list("pods", "default",
                                    label_selector="app=nodekill")
            return [p for p in pods if p.metadata.deletion_timestamp is None]

        def bound_count():
            return sum(1 for p in live_pods() if p.spec.node_name)

        # steady in-flight state before the kill: at least half placed
        if not wait_until(lambda: bound_count() >= replicas // 2,
                          deadline):
            result.detail = "never reached half-bound before kill"
            return result

        result.kill_at_s = round(clock.monotonic() - t0, 3)
        post_kill["armed"] = True
        killed = chaos_nodes.kill()
        t_kill = clock.monotonic()
        result.killed = killed
        result.schedule_replayed = (
            killed == node_plan.kill_set(fleet.node_names())
            == node_plan.schedule(fleet.node_names())["kill"])
        dead = set(killed)

        def converged():
            pods = live_pods()
            if len(pods) != replicas:
                return False
            if not all(p.spec.node_name and p.spec.node_name not in dead
                       and p.status.phase == "Running" for p in pods):
                return False
            # the fleet-wide quiesce gate: NOTHING (any namespace,
            # terminating or not) still bound to a dead node
            all_pods, _ = registry.list("pods", "default")
            return not any(p.spec.node_name in dead for p in all_pods)

        ok = wait_until(converged, deadline)
        result.converge_s = round(clock.monotonic() - t_kill, 3)
        result.converged = ok
        result.evictions = node_ctl.evictions_total
        result.partition_halts = node_ctl.partition_halts_total
        result.rebinds = post_kill["count"]
        all_pods, _ = registry.list("pods", "default")
        result.dead_bound = sum(1 for p in all_pods
                                if p.spec.node_name in dead)
        if not ok:
            pods = live_pods()
            result.detail = (
                f"{len(pods)}/{replicas} live, "
                f"{sum(1 for p in pods if p.status.phase == 'Running')} "
                f"running, {result.dead_bound} on dead nodes")
        return result
    finally:
        factory.scheduled_observers.remove(count_rebind)
        chaos_nodes.stop()
        node_ctl.stop()
        rc_mgr.stop()
        sched.stop()
        factory.stop()
        fleet.stop()


def run_partition_gate(n_nodes: int = 20, freeze_fraction: float = 0.6,
                       seed: int = 0, timeout: float = 60.0,
                       heartbeat_interval: float = 0.3,
                       monitor_period: float = 0.1,
                       monitor_grace_period: float = 1.0,
                       pod_eviction_timeout: float = 0.2,
                       clock: Optional[Clock] = None) -> Dict:
    """The partition safety-valve acceptance: freeze the heartbeats of
    > unhealthy_threshold of the fleet at once -> the NodeController
    must HALT evictions (zero pods deleted while halted), then resume
    after the heartbeats thaw. Returns the observations the test (and
    anyone replaying the README workflow) asserts on."""
    clock = clock or REAL
    registry = Registry()
    client = InProcClient(registry)
    fleet = HollowFleet(client, n_nodes,
                        heartbeat_interval=heartbeat_interval,
                        jitter_seed=seed).run()
    node_ctl = NodeController(
        client, monitor_period=monitor_period,
        monitor_grace_period=monitor_grace_period,
        pod_eviction_timeout=pod_eviction_timeout,
        eviction_qps=1000.0, eviction_burst=1000).run()
    plan = NodeFaultPlan(seed=seed, freeze_fraction=freeze_fraction)
    chaos_nodes = NodeChaos(fleet, plan)
    out = {"halted": False, "evictions_while_halted": 0,
           "resumed": False, "halts": 0, "frozen": []}

    def wait_until(cond, t):
        deadline = clock.monotonic() + t
        while clock.monotonic() < deadline:
            if cond():
                return True
            clock.sleep(0.05)
        return cond()

    try:
        if not wait_until(
                lambda: len(registry.list("nodes")[0]) >= n_nodes,
                timeout / 3):
            return out
        # a victim pod on a frozen node: were the valve broken, the
        # mass-Unknown marking would evict it
        victim_host = sorted(plan.freeze_set(fleet.node_names()))[0]
        pod = _bench_pod(0)
        pod.spec.node_name = victim_host
        client.create("pods", pod)

        out["frozen"] = chaos_nodes.freeze()
        halted = wait_until(lambda: node_ctl.evictions_halted, timeout / 3)
        out["halted"] = halted
        # hold the partition well past grace + eviction timeout: zero
        # evictions may be issued while the valve is engaged
        clock.sleep(3 * (monitor_grace_period + pod_eviction_timeout))
        out["evictions_while_halted"] = node_ctl.evictions_total
        chaos_nodes.thaw()
        out["resumed"] = wait_until(
            lambda: not node_ctl.evictions_halted, timeout / 3)
        out["halts"] = node_ctl.partition_halts_total
        return out
    finally:
        chaos_nodes.stop()
        node_ctl.stop()
        fleet.stop()
