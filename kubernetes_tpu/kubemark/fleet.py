"""Multiplexed hollow-node fleet.

One object simulates N hollow kubelets against the apiserver:

- registers N Node objects (capacity + Ready/OutOfDisk conditions, the
  fields the scheduler's node filter reads, factory.go:241-256)
- heartbeats all of them on one timer (NodeStatus updates, the signal the
  node-lifecycle controller watches)
- watches ALL pods on one informer and dispatches by spec.nodeName,
  confirming each bound pod Running through one batched status pump —
  the hollow kubelet contract (pkg/kubemark/hollow_kubelet.go: fake
  runtime, instant success)

The per-node agent (agents.HollowKubelet) stays the faithful single-node
implementation; this fleet is the scale harness (5k nodes in one process,
the start-kubemark.sh role).
"""

from __future__ import annotations

import queue
import random
import threading
from dataclasses import replace
from typing import Dict, Iterable, List, Optional, Set

from .. import obs
from ..agents.hollow_node import confirm_pod_deletion
from ..api.cache import Informer, meta_namespace_key
from ..core import types as api
from ..core.errors import AlreadyExists, Conflict, NotFound
from ..core.quantity import parse_quantity


class HollowFleet:
    def __init__(self, client, n_nodes: int, name_prefix: str = "hollow-",
                 cpu: str = "4", memory: str = "32Gi", max_pods: int = 40,
                 heartbeat_interval: float = 10.0,
                 labels_for=None, jitter_seed: Optional[int] = None,
                 status_chunk: int = 0):
        """labels_for: optional fn(index) -> labels dict (zones etc.).
        jitter_seed: seeds the heartbeat-phase RNG so a chaos/soak
        harness's beat schedule is reproducible; None keeps the
        process RNG (beats must decohere, not share a phase).
        status_chunk: 0 drains each queued status burst into one
        txn-routed update_status_batch (one revision window); a
        positive value restores the old capped per-chunk loop —
        bench.py --txn-ab uses 1024 as the control arm."""
        self.client = client
        self._jitter_rng = (random.Random(f"{jitter_seed}:heartbeat")
                            if jitter_seed is not None else random.Random())
        self.n_nodes = n_nodes
        self.name_prefix = name_prefix
        self.cpu = cpu
        self.memory = memory
        self.max_pods = max_pods
        self.heartbeat_interval = heartbeat_interval
        self.status_chunk = status_chunk
        self.labels_for = labels_for or (lambda i: {})
        self._names = [f"{name_prefix}{i:05d}" for i in range(n_nodes)]
        self._running: Dict[str, str] = {}  # pod key -> node
        # chaos surfaces (chaos.nodes.NodeChaos drives these):
        # dead      — the host died: no heartbeats, no pod confirms
        # frozen    — heartbeats suppressed (partition sim); kubelet alive
        # not_ready — heartbeats continue but report Ready=False (flap sim)
        self._dead: Set[str] = set()
        self._frozen: Set[str] = set()
        self._not_ready: Set[str] = set()
        self._lock = threading.Lock()
        self._status_q: "queue.Queue[Optional[api.Pod]]" = queue.Queue()
        # (ts, shared Ready conditions, shared running state) — see
        # _running_status
        self._status_shared = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._informer: Optional[Informer] = None

    # ---------------------------------------------------------- node side

    def _node_object(self, i: int) -> api.Node:
        ts = api.now_rfc3339()
        name = self._names[i]
        with self._lock:
            ready = "False" if name in self._not_ready else "True"
        return api.Node(
            metadata=api.ObjectMeta(name=name,
                                    labels=self.labels_for(i)),
            status=api.NodeStatus(
                capacity={"cpu": parse_quantity(self.cpu),
                          "memory": parse_quantity(self.memory),
                          "pods": parse_quantity(str(self.max_pods))},
                conditions=[
                    api.NodeCondition(type="Ready", status=ready,
                                      reason=("KubeletReady" if ready == "True"
                                              else "KubeletNotReady"),
                                      last_heartbeat_time=ts),
                    api.NodeCondition(type="OutOfDisk", status="False",
                                      reason="KubeletHasSufficientDisk",
                                      last_heartbeat_time=ts)],
                node_info=api.NodeSystemInfo(
                    kubelet_version="hollow-fleet",
                    container_runtime_version="fake://0")))

    def register_all(self) -> None:
        for i in range(self.n_nodes):
            for attempt in range(5):
                try:
                    self.client.create("nodes", self._node_object(i))
                    break
                except AlreadyExists:
                    break  # already registered from a prior life
                except Exception:
                    # transient (connection loss, injected fault): the
                    # heartbeat's NotFound path would heal this, but a
                    # long heartbeat interval must not leave the node
                    # unregistered for minutes — retry here first
                    self._stop.wait(0.05 * (attempt + 1))
                    if self._stop.is_set():
                        return

    def _heartbeat_one(self, i: int, retries: int = 2) -> None:
        name = self._names[i]
        with self._lock:
            if name in self._dead or name in self._frozen:
                return  # a dead/partitioned kubelet posts nothing
        for attempt in range(retries + 1):
            try:
                node = self.client.get("nodes", name)
                fresh = self._node_object(i)
                self.client.update_status("nodes", replace(
                    node, status=replace(node.status,
                                         conditions=fresh.status.conditions)))
                return
            except NotFound:
                # re-register a node the apiserver lost (or whose
                # registration never landed). ANY failure here must be
                # swallowed — an exception raised inside this handler
                # would escape the outer try and kill the fleet's one
                # heartbeat thread (a transient create fault at 1k
                # nodes under injected chaos did exactly that); the
                # next beat retries the heal
                try:
                    self.client.create("nodes", self._node_object(i))
                except AlreadyExists:
                    pass  # the heal (or a replayed create) landed
                except Exception:
                    pass
                return
            except Exception:
                # transient (injected fault, connection loss): retry
                # with a short backoff instead of leaving the heartbeat
                # stale a whole period — at 5k nodes and 5% faults,
                # period-long gaps push healthy nodes over the
                # controller's grace window
                if attempt >= retries or self._stop.is_set():
                    return
                self._stop.wait(0.05 * (attempt + 1))

    def _heartbeat_loop(self) -> None:
        # staggered: real kubelets beat independently, not in one
        # synchronized wave — a multiplexed fleet that updated all N
        # node statuses at once invalidated every cached node encoding
        # in the same instant, turning the next LIST into a full
        # re-encode spike (1.9s at 5k nodes, over the 1s API SLO). Beat
        # one shard per tick so each node still beats once per
        # heartbeat_interval; each tick draws full jitter (uniform over
        # [0.5, 1.5) of the nominal tick) so shards decohere over time
        # instead of 5k nodes settling into one phase-locked wave.
        shards = 10
        tick = self.heartbeat_interval / shards
        shard = 0
        rng = self._jitter_rng
        while not self._stop.is_set():
            self._stop.wait(tick * rng.uniform(0.5, 1.5))
            if self._stop.is_set():
                return
            self._heartbeat_shard(shard, shards)
            shard = (shard + 1) % shards

    def _heartbeat_shard(self, shard: int, shards: int) -> None:
        for i in range(shard, len(self._names), shards):
            if self._stop.is_set():
                return
            self._heartbeat_one(i)

    # ----------------------------------------------------- chaos surface

    def node_names(self) -> List[str]:
        return list(self._names)

    def kill_nodes(self, names: Iterable[str]) -> List[str]:
        """Hard-kill these hollow hosts: heartbeats stop, bound pods are
        never confirmed Running again, deletion marks are never acked.
        The Node API objects stay behind with stale heartbeats — exactly
        the wire a dead machine leaves."""
        names = [n for n in names if n in set(self._names)]
        with self._lock:
            self._dead.update(names)
        return names

    def dead_nodes(self) -> Set[str]:
        with self._lock:
            return set(self._dead)

    def live_nodes(self) -> List[str]:
        with self._lock:
            return [n for n in self._names if n not in self._dead]

    def freeze_heartbeats(self, names: Iterable[str]) -> None:
        """Suppress heartbeats (master-side partition sim): the kubelet
        is alive — pods still confirm — but its status updates never
        arrive, so the controller sees the heartbeat go stale."""
        with self._lock:
            self._frozen.update(names)

    def thaw_heartbeats(self, names: Optional[Iterable[str]] = None) -> None:
        with self._lock:
            if names is None:
                self._frozen.clear()
            else:
                self._frozen.difference_update(names)

    def set_not_ready(self, names: Iterable[str], not_ready: bool) -> None:
        """Flap surface: keep heartbeating but report Ready=False (a
        sick-but-alive kubelet). Toggling this is how NodeChaos bounces
        a node Ready<->NotReady inside the controller's grace window."""
        with self._lock:
            if not_ready:
                self._not_ready.update(names)
            else:
                self._not_ready.difference_update(names)

    # ----------------------------------------------------------- pod side

    def _on_pod(self, pod: api.Pod) -> None:
        node = pod.spec.node_name
        if not node or not node.startswith(self.name_prefix):
            return
        with self._lock:
            if node in self._dead:
                # a dead kubelet neither confirms Running nor acks
                # deletion marks — the pod object just sits there until
                # the NodeController evicts it
                return
        if pod.metadata.deletion_timestamp is not None:
            # graceful deletion's node half (hollow: nothing to drain):
            # confirm with the grace-0 uid-guarded delete so marked
            # pods terminate instead of sitting Terminating forever
            # (transient failures retry off-thread — no further watch
            # event will re-drive a marked pod)
            self._on_pod_delete(pod)
            confirm_pod_deletion(self.client, pod)
            return
        if pod.status.phase in ("Running", "Succeeded", "Failed"):
            return
        key = meta_namespace_key(pod)
        with self._lock:
            if key in self._running:
                return
            self._running[key] = node
        self._status_q.put(pod)

    def _on_pod_delete(self, pod: api.Pod) -> None:
        with self._lock:
            self._running.pop(meta_namespace_key(pod), None)

    def _running_status(self, pod: api.Pod, ts: str) -> api.PodStatus:
        # batch-invariant sub-objects (Ready condition, running state at
        # ts) are built once per timestamp and SHARED across the pods of
        # a status tile — the framework's replace-don't-mutate contract
        # makes that safe, and it drops ~4 dataclass constructions per
        # pod off the confirm-Running whale (PROFILE_e2e.md). Per-pod
        # data (uid-bearing container_id, start_time) stays per-pod.
        shared = self._status_shared
        if shared is None or shared[0] != ts:
            shared = (ts,
                      [api.PodCondition(type="Ready", status="True")],
                      api.ContainerState(
                          running=api.ContainerStateRunning(started_at=ts)))
            self._status_shared = shared
        _, conditions, state = shared
        return api.PodStatus(
            phase="Running",
            conditions=conditions,
            host_ip="10.0.0.1", pod_ip="10.244.0.2",
            start_time=pod.status.start_time or ts,
            container_statuses=[api.ContainerStatus(
                name=c.name, ready=True, image=c.image,
                container_id=f"fake://{pod.metadata.uid}/{c.name}",
                state=state)
                for c in pod.spec.containers])

    def _status_pump(self) -> None:
        while True:
            pod = self._status_q.get()
            if pod is None:
                return
            # drain a whole burst: under a scheduler tile-commit, the
            # watch hands this queue thousands of freshly-bound pods —
            # confirm them Running in ONE batched store pass instead of
            # per-pod writes fighting the GIL (per-object semantics are
            # unchanged; see registry.update_status_batch)
            # With commit_txn routing the whole burst lands in one
            # revision window under one ledger-lock acquisition, so the
            # old 1024 cap (which bounded the per-chunk lock hold when
            # each chunk was a separate store.batch) is off by default.
            # A positive status_chunk restores the capped loop as the
            # --txn-ab control arm — see sched/batch.py commit_chunk.
            cap = self.status_chunk or float("inf")
            batch = [pod]
            while len(batch) < cap:
                try:
                    nxt = self._status_q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._status_q.put(None)  # re-arm shutdown sentinel
                    break
                batch.append(nxt)
            with self._lock:
                if self._dead:
                    # nodes killed after their pods were queued: the
                    # dead kubelet must not confirm them
                    batch = [p for p in batch
                             if p.spec.node_name not in self._dead]
            if not batch:
                continue
            ts = api.now_rfc3339()
            updated = [api.fast_replace(p,
                                        status=self._running_status(p, ts))
                       for p in batch]
            tr = obs.tracer()
            span = obs.NOOP
            if tr.enabled:
                # "confirm" stage, burst-granular (first pod's
                # annotation context as exemplar parent): fleet status
                # batch -> committed closes the pod's e2e decomposition
                span = tr.start_span("fleet.confirm",
                                     parent=obs.ctx_of(batch[0]),
                                     stage="confirm",
                                     attrs={"pods": len(batch)})
            try:
                with obs.use(span):
                    batched = False
                    if len(updated) > 1:
                        try:
                            self.client.update_status_batch("pods",
                                                            updated)
                            batched = True
                        except Exception:
                            # degrade to singles: per-pod NotFound
                            # handling
                            pass
                    if not batched:
                        for p, u in zip(batch, updated):
                            self._status_one(p, u)
            finally:
                tr.end(span)

    def _status_one(self, pod: api.Pod, updated: api.Pod) -> None:
        try:
            try:
                self.client.update_status(
                    "pods", updated, pod.metadata.namespace)
            except Conflict:
                # stale rv (a writer landed between our bind event and
                # this confirm): re-read and re-stamp like the real
                # kubelet's status manager — retrying the ORIGINAL
                # object would 409 forever (the store rev only
                # advances)
                fresh = self.client.get("pods", pod.metadata.name,
                                        pod.metadata.namespace)
                self.client.update_status(
                    "pods", api.fast_replace(
                        fresh, status=updated.status),
                    pod.metadata.namespace)
        except NotFound:
            self._on_pod_delete(pod)
        except Exception:
            # transient: retry unless the fleet is shutting down
            if not self._stop.is_set():
                with self._lock:
                    wanted = meta_namespace_key(pod) in self._running
                if wanted:
                    self._status_q.put(pod)

    # ---------------------------------------------------------- lifecycle

    def running_count(self) -> int:
        with self._lock:
            return len(self._running)

    def run(self) -> "HollowFleet":
        self.register_all()
        # assigned pods only — the same spec.nodeName watch a real
        # kubelet makes (its field selector names one node; the fleet's
        # dispatch-by-nodeName covers all of its nodes with one stream),
        # and the server-side filter keeps the firehose of pending-pod
        # ADDED events out of this informer's queue entirely
        self._informer = Informer(
            self.client, "pods", field_selector="spec.nodeName!=",
            on_add=self._on_pod,
            on_update=lambda old, new: self._on_pod(new),
            on_delete=self._on_pod_delete).start()
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True,
                              name="fleet-heartbeat")
        pump = threading.Thread(target=self._status_pump, daemon=True,
                                name="fleet-status-pump")
        self._threads = [hb, pump]
        hb.start()
        pump.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._informer:
            self._informer.stop()
        self._status_q.put(None)
