"""Process-crash soak: the durability/HA acceptance harness.

Stands up the full control plane over HTTP with a WAL-backed store and
REDUNDANT singletons — two batch schedulers and two controller-managers,
each pair under lease-based leader election (utils/leaderelection.py) —
with every component client behind the seeded API-fault injector. An RC
drives a commit storm, and a seeded `CrashPlan` kills processes at
deterministic points of its progress:

  apiserver kill        the store is REBUILT from its WAL
                        (Store.recover) and a fresh server takes the
                        same port; the gate compares the recovered
                        ledger against the pre-crash one — same
                        revision, same live object set, no resurrected
                        expired keys — then watchers re-list and the
                        fleet reconverges
  active-scheduler kill the standby waits out the lease, rebuilds its
                        device state from a fresh snapshot, and binds
                        the remainder (zero duplicate bindings: CAS)
  active-manager kill   the standby controller-manager resumes
                        replication under a new fencing term

Convergence gates (the ISSUE-7 acceptance bar): every replica Running
on a node, zero duplicate bindings ever observed, at most one lease
holder per fencing term, the applied kill schedule equal to the plan's
pure replay, and the durability counters (wal_records_total,
wal_recoveries_total, leader_transitions_total) moving. Shared
verbatim by the pytest gates (tests/test_chaos.py) and the bench arm
(bench.py --crash-seed), so the artifact records exactly the invariant
the test enforces.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api.client import HttpClient, InProcClient
from ..api.registry import Registry
from ..api.server import ApiServer
from ..chaos import ChaosClient, CrashChaos, CrashPlan, FaultPlan
from ..controllers.manager import ControllerManager
from ..core import types as api
from ..core.store import Store
from ..core.errors import AlreadyExists
from ..obs import tracer as _obs_tracer
from ..obs.flightrec import FlightRecorder
from ..sched.batch import BatchScheduler
from ..sched.factory import ConfigFactory
from ..utils.clock import REAL, Clock
from ..utils.leaderelection import LeaderElectionConfig, LeaderElector
from ..utils.metrics import global_metrics
from .benchmark import _bench_pod
from .fleet import HollowFleet

#: counters the soak gates on (satellite: utils/metrics.py
#: DURABILITY_COUNTERS) — recorded as before/after deltas because the
#: global registry is process-wide
_GATED_COUNTERS = ("wal_records_total", "wal_recoveries_total",
                   "leader_transitions_total",
                   "lease_renew_failures_total")


@dataclass
class CrashSoakResult:
    converged: bool
    n_nodes: int
    replicas: int
    #: kill points actually applied (bound-pod progress), per target
    killed: Dict[str, int] = field(default_factory=dict)
    #: the plan's pure replay — the reproducibility gate
    schedule: Dict[str, int] = field(default_factory=dict)
    schedule_replayed: bool = True
    #: apiserver-kill recovery: the pre-crash vs recovered ledger
    recovery: Dict = field(default_factory=dict)
    #: (uid, old_node, new_node) triples — gate: empty
    duplicate_bindings: List[Tuple[str, str, str]] = \
        field(default_factory=list)
    #: every (lease, term) observed with more than one holder — gate:
    #: empty (at most one holder per fencing term)
    term_violations: List = field(default_factory=list)
    #: highest fencing term observed per lease
    terms: Dict[str, int] = field(default_factory=dict)
    #: durability-counter deltas across the run
    counters: Dict[str, float] = field(default_factory=dict)
    #: which replica (a/b) held each singleton at quiesce
    leaders_at_end: Dict[str, str] = field(default_factory=dict)
    converge_s: float = 0.0
    #: flight-recorder bundles written (flight_dir runs): one per kill
    flight_bundles: List[str] = field(default_factory=list)
    detail: str = ""

    def as_dict(self) -> Dict:
        return asdict(self)


def run_crash_soak(n_nodes: int = 6, replicas: int = 24, seed: int = 0,
                   fault_rate: float = 0.05,
                   wal_dir: Optional[str] = None,
                   fsync_policy: str = "batch",
                   timeout: float = 180.0,
                   lease_duration: float = 1.5,
                   renew_deadline: float = 1.0,
                   retry_period: float = 0.15,
                   heartbeat_interval: float = 1.0,
                   post_kill_scale: Optional[int] = None,
                   clock: Optional[Clock] = None,
                   flight_dir: Optional[str] = None
                   ) -> CrashSoakResult:
    """One seeded crash soak; see the module docstring for the
    scenario. Lease timings default to soak-compressed values (the
    production 15s/10s/2s would make each failover a quarter-minute
    wait).

    post_kill_scale (default replicas//2): after the last kill the RC
    is scaled UP by this many replicas — a wave that only the standby
    controller-manager can create and only the standby scheduler can
    bind, so convergence structurally proves both failovers (and the
    lease takeovers advance each fencing term past the killed
    leader's)."""
    clock = clock or REAL
    own_tmp = wal_dir is None
    wal_dir = wal_dir or tempfile.mkdtemp(prefix="kube-wal-")
    base = {name: global_metrics.counter_sum(name)
            for name in _GATED_COUNTERS}
    store = Store(wal_dir=wal_dir, fsync_policy=fsync_policy)
    registry = Registry(store=store)
    srv = ApiServer(registry, port=0).start()
    port = srv.port
    plan = FaultPlan(seed=seed, error_rate=fault_rate)
    chaos = ChaosClient(HttpClient(srv.url), plan)
    crash_plan = CrashPlan(seed=seed)
    crash = CrashChaos(crash_plan, total=replicas)
    result = CrashSoakResult(converged=False, n_nodes=n_nodes,
                             replicas=replicas,
                             schedule=crash_plan.schedule(replicas))
    recorder = (FlightRecorder(flight_dir, clock=clock)
                if flight_dir else None)

    # ---- invariant trackers ride the live registry directly (no
    # chaos, no HTTP) and re-point after the apiserver restart
    ctx = {"registry": registry, "store": store}
    lock = threading.Lock()
    bound_to: Dict[str, str] = {}          # pod uid -> node
    duplicates: List[Tuple[str, str, str]] = []
    term_holders: Dict[Tuple[str, int], set] = {}
    stop_tracker = threading.Event()

    def track():
        while not stop_tracker.is_set():
            reg = ctx["registry"]
            try:
                pods, _ = reg.list("pods", "default",
                                   label_selector="app=crash")
                leases, _ = reg.list("leases", "kube-system")
            except Exception:
                clock.sleep(0.03)
                continue
            with lock:
                for p in pods:
                    node = p.spec.node_name
                    if not node:
                        continue
                    prev = bound_to.get(p.metadata.uid)
                    if prev is not None and prev != node:
                        duplicates.append((p.metadata.uid, prev, node))
                    bound_to[p.metadata.uid] = node
                for l in leases:
                    if l.spec.holder_identity:
                        term_holders.setdefault(
                            (l.metadata.name, l.spec.lease_transitions),
                            set()).add(l.spec.holder_identity)
            clock.sleep(0.03)

    tracker = threading.Thread(target=track, daemon=True,
                               name="crash-soak-tracker")
    tracker.start()

    def bound_count() -> int:
        with lock:
            return len(bound_to)

    # ---- the redundant control plane
    def lease_cfg(name: str, ident: str) -> LeaderElectionConfig:
        return LeaderElectionConfig(
            lease_name=name, identity=ident, namespace="kube-system",
            lease_duration=lease_duration, renew_deadline=renew_deadline,
            retry_period=retry_period)

    fleet = HollowFleet(chaos, n_nodes,
                        heartbeat_interval=heartbeat_interval,
                        jitter_seed=seed).run()
    factories = {k: ConfigFactory(chaos, rate_limit=False).start()
                 for k in ("a", "b")}
    scheds = {k: BatchScheduler(
        factories[k].create_batch(),
        elector=LeaderElector(chaos,
                              lease_cfg("batch-scheduler", f"sched-{k}"))
    ).run() for k in ("a", "b")}
    managers = {k: ControllerManager(
        chaos, elect=lease_cfg("controller-manager", f"cm-{k}")).run()
        for k in ("a", "b")}

    def wait_until(cond, deadline):
        while clock.monotonic() < deadline:
            if cond():
                return True
            clock.sleep(0.05)
        return cond()

    def active(pair):
        for k, comp in pair.items():
            if comp.is_leader:
                return k, comp
        return None, None

    try:
        deadline = clock.monotonic() + timeout
        if not wait_until(
                lambda: len(factories["a"].node_lister.list()) >= n_nodes,
                deadline):
            result.detail = "fleet never registered"
            return result

        rc = api.ReplicationController(
            metadata=api.ObjectMeta(name="crash", namespace="default"),
            spec=api.ReplicationControllerSpec(
                replicas=replicas, selector={"app": "crash"},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels={"app": "crash"}),
                    spec=_bench_pod(0).spec)))
        while True:  # RC creation rides the fault injector too
            try:
                chaos.create("replicationcontrollers", rc)
                break
            except AlreadyExists:
                break  # a replayed create already committed the RC
            except Exception:
                if clock.monotonic() > deadline:
                    result.detail = "rc create never landed"
                    return result
                clock.sleep(0.05)

        # ---- apply the crash schedule as progress crosses each point
        for point, target in crash.pending():
            if not wait_until(lambda: bound_count() >= point, deadline):
                result.detail = (f"never reached kill point {point} "
                                 f"for {target} ({bound_count()} bound)")
                return result
            if target == "apiserver":
                srv.stop()
                # the dead process's ledger, sampled for the gate (the
                # WAL on disk is what recovery actually reads)
                store.wal_close()
                pre_rev = store.current_revision
                pre_live = {k: v[1] for k, v in store._data.items()
                            if not store._expired(v, clock.now())}
                recovered = Store.recover(wal_dir,
                                          fsync_policy=fsync_policy)
                now = clock.now()
                rec_live = {k: v[1] for k, v in recovered._data.items()
                            if not recovered._expired(v, now)}
                result.recovery = {
                    "pre_revision": pre_rev,
                    "recovered_revision": recovered.current_revision,
                    "revision_match":
                        recovered.current_revision == pre_rev,
                    "live_set_match": rec_live == pre_live,
                    **recovered.recovery_stats,
                }
                registry = Registry(store=recovered)
                ctx["registry"] = registry
                ctx["store"] = recovered
                srv = ApiServer(registry, host="127.0.0.1",
                                port=port).start()
            elif target == "scheduler":
                if not wait_until(
                        lambda: active(scheds)[0] is not None, deadline):
                    result.detail = "no scheduler ever led"
                    return result
                _k, leader = active(scheds)
                leader.kill()
            else:  # controller-manager
                if not wait_until(
                        lambda: active(managers)[0] is not None,
                        deadline):
                    result.detail = "no controller-manager ever led"
                    return result
                _k, leader = active(managers)
                leader.kill()
            crash.record(target, point)
            if recorder is not None:
                # chaos-kill post-mortem: the plan position + span
                # buffer at the instant of the kill (the series tail
                # comes from the workload soak's recorder — this soak
                # has no scraper, and the recorder writes what exists)
                recorder.dump(f"chaos-kill-{target}",
                              tracer=_obs_tracer(), chaos=crash,
                              extra={"point": point, "target": target})

        result.killed = crash.trace()
        if recorder is not None:
            result.flight_bundles = list(recorder.bundles)
        result.schedule_replayed = (
            result.killed == crash_plan.schedule(replicas)
            == result.schedule)
        t_kill = clock.monotonic()

        # the failover-proof wave: these pods do not exist yet, so the
        # DEAD controller-manager cannot have created them nor the dead
        # scheduler bound them — converging past this scale-up means
        # the standbys actually took over (see docstring)
        final_replicas = replicas + (post_kill_scale
                                     if post_kill_scale is not None
                                     else replicas // 2)
        while True:
            try:
                sc = chaos.get_scale("replicationcontrollers", "crash",
                                     "default")
                sc.spec.replicas = final_replicas
                chaos.update_scale("replicationcontrollers", "crash",
                                   sc, "default")
                break
            except Exception:
                if clock.monotonic() > deadline:
                    result.detail = "post-kill scale-up never landed"
                    return result
                clock.sleep(0.05)

        def converged():
            reg = ctx["registry"]
            try:
                pods, _ = reg.list("pods", "default",
                                   label_selector="app=crash")
            except Exception:
                return False
            live = [p for p in pods
                    if p.metadata.deletion_timestamp is None]
            return (len(live) == final_replicas
                    and all(p.spec.node_name for p in live)
                    and all(p.status.phase == "Running" for p in live))

        ok = wait_until(converged, deadline)
        result.converge_s = round(clock.monotonic() - t_kill, 3)
        result.converged = ok
        with lock:
            result.duplicate_bindings = list(duplicates)
            result.term_violations = [
                (lease, term, sorted(holders))
                for (lease, term), holders in sorted(term_holders.items())
                if len(holders) > 1]
            result.terms = {}
            for (lease, term), _h in term_holders.items():
                result.terms[lease] = max(result.terms.get(lease, 0),
                                          term)
        result.leaders_at_end = {
            "scheduler": active(scheds)[0] or "",
            "controller-manager": active(managers)[0] or ""}
        result.counters = {
            name: round(global_metrics.counter_sum(name) - base[name], 1)
            for name in _GATED_COUNTERS}
        if not ok:
            reg = ctx["registry"]
            pods, _ = reg.list("pods", "default",
                               label_selector="app=crash")
            live = [p for p in pods
                    if p.metadata.deletion_timestamp is None]
            result.detail = (
                f"{len(live)}/{replicas} live, "
                f"{sum(1 for p in live if p.spec.node_name)} bound, "
                f"{sum(1 for p in live if p.status.phase == 'Running')} "
                f"running")
        return result
    finally:
        stop_tracker.set()
        for m in managers.values():
            m.stop()
        for s in scheds.values():
            s.stop()
        for f in factories.values():
            f.stop()
        fleet.stop()
        srv.stop()
        ctx["store"].wal_close()
        if own_tmp:
            import shutil
            shutil.rmtree(wal_dir, ignore_errors=True)


# ------------------------------------------------------------ WAL bench

def run_wal_bench(n_records: int = 5000,
                  wal_dir: Optional[str] = None) -> Dict:
    """The fsync-policy A/B plus recovery timing (bench.py --wal-dir's
    `durability.wal` section): a create storm against a WAL-backed
    store under each policy, then a recovery replay of the `batch` arm
    measuring wall-clock and replayed records/s."""
    import shutil

    out: Dict = {"records": n_records}
    base = wal_dir or tempfile.mkdtemp(prefix="kube-walbench-")
    keep_dir = None
    try:
        for policy in ("always", "batch"):
            d = os.path.join(base, policy)
            st = Store(wal_dir=d, fsync_policy=policy)
            t0 = time.monotonic()
            for i in range(n_records):
                st.create(f"/registry/pods/default/w{i:06d}",
                          _bench_pod(i))
            elapsed = time.monotonic() - t0
            st.wal_close()
            out[policy] = {
                "elapsed_s": round(elapsed, 3),
                "records_per_sec": round(n_records / elapsed, 1)}
            keep_dir = d if policy == "batch" else keep_dir
        rec = Store.recover(keep_dir)
        stats = rec.recovery_stats
        out["recovery"] = {
            "wall_s": stats["seconds"],
            "replayed_records": stats["replayed_records"],
            "replayed_records_per_sec": round(
                stats["replayed_records"] / stats["seconds"], 1)
            if stats["seconds"] else None,
            "recovered_revision": stats["recovered_revision"]}
        rec.wal_close()
        return out
    finally:
        if wal_dir is None:
            shutil.rmtree(base, ignore_errors=True)
