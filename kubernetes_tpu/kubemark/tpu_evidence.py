"""Opportunistic real-TPU evidence capture.

The tunneled TPU on this box wedges for hours at a time (the FIRST
dispatch hangs forever, including backend creation). Probing only at
driver time produced three straight rounds of `platform: cpu-fallback`
benchmarks with zero real-TPU artifacts. This module is the fix:
``tools/tpu_watch.py`` probes the tunnel on a schedule for the whole
round and, the moment a probe succeeds, runs this capture in a bounded
subprocess. Results land in ``TPU_EVIDENCE.json`` (written section by
section, atomic rename at each flush, so a mid-capture wedge still
leaves partial evidence) and ``bench.py`` merges the freshest evidence
into its JSON line as a ``tpu`` section even when its own end-of-round
probe fails.

Captured sections:

- ``dispatch``: tiny-dispatch roundtrip latency percentiles (the tunnel
  adds ~86ms per fetch; the tile pipeline is shaped around that).
- ``engine``: engine-only scoring throughput at the 5k-node/30k-pod
  north-star shape (BASELINE.json) via the production 8192-pod
  ``run_chunked`` tile, plus the 1k/3k point.
- ``pallas``: the predicate-filter kernel compiled and executed under
  REAL Mosaic (interpret=False on a tpu backend), bit-compared against
  the XLA probe — then a forced-rejection exercise: a genuinely
  Mosaic-unloweable kernel is swapped into pallas_filter._filter_call
  and BatchEngine.filter_masks must catch the real rejection, latch
  ``_pallas_broken``, and return the XLA result (engine.py:528-544 has
  never seen a real rejection before this).
- ``e2e``: the full kubemark pipeline (registry + watch fan-out + FIFO
  drain + incremental encode + device scan + batched CAS bind) on the
  default platform, 5k nodes / 30k pods.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback


def _utc() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _atomic_write_json(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


# ---------------------------------------------------------------- chip lock
# One tunneled chip, two writers (the round-long watcher's opportunistic
# captures and bench.py's headline run). A tiny advisory file lock keeps
# them from measuring under contention: whoever holds it owns the chip;
# the other side defers (watcher) or waits (bench). Ownership is by pid —
# release never unlinks a lock another process has since written, so a
# slow capture finishing late cannot delete the bench run's hold.

def chip_lock_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".tpu_capture.lock")


def read_chip_lock() -> "dict | None":
    try:
        with open(chip_lock_path()) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def foreign_chip_lock_fresh(max_age: float = 2700.0) -> bool:
    """A fresh lock held by ANOTHER process; stale records (crashed
    holder) don't count."""
    rec = read_chip_lock()
    return (rec is not None and rec.get("pid") != os.getpid()
            and time.time() - rec.get("ts", 0) <= max_age)


def try_acquire_chip_lock(who: str = "") -> bool:
    """Atomic test-and-set: returns False when another process holds a
    fresh lock (the caller must not touch the chip). A stale record
    (crashed holder) or our own previous record is reclaimed by
    atomically renaming it aside first — two racing reclaimers can't
    both win (exactly one rename succeeds), and a live holder's fresh
    record is never stomped."""
    path = chip_lock_path()
    rec = {"pid": os.getpid(), "ts": time.time(), "who": who}
    for _ in range(2):
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            if foreign_chip_lock_fresh():
                return False
            claim = f"{path}.reclaim.{os.getpid()}"
            try:
                os.rename(path, claim)  # atomic: one reclaimer wins
            except OSError:
                continue  # lost the race — re-check who holds it now
            try:
                os.unlink(claim)
            except OSError:
                pass
            continue  # retry the exclusive create
        with os.fdopen(fd, "w") as f:
            json.dump(rec, f)
        return True
    return False


def refresh_chip_lock() -> None:
    """Re-stamp ts on a lock this process owns (a long headline run must
    not age past the staleness window and lose the chip mid-measure)."""
    rec = read_chip_lock()
    if rec is not None and rec.get("pid") == os.getpid():
        _atomic_write_json(chip_lock_path(), dict(rec, ts=time.time()))


def release_chip_lock() -> None:
    rec = read_chip_lock()
    if rec is not None and rec.get("pid") == os.getpid():
        try:
            os.unlink(chip_lock_path())
        except OSError:
            pass


class _Evidence:
    """Accumulates sections, flushing the artifact after each one so a
    tunnel wedge mid-capture loses only the in-flight section. Each
    flush also folds completed sections into the per-section BEST
    artifact — a capture killed mid-e2e still contributes its engine
    number to the ceiling."""

    def __init__(self, path: str, best_path: str | None = None):
        self.path = path
        self.best_path = best_path
        self.doc = {"ts_start": _utc(), "complete": False, "sections": {}}

    def flush(self):
        _atomic_write_json(self.path, self.doc)
        if self.best_path:
            try:
                merge_best(self.doc, self.best_path)
            except Exception:
                # best-file trouble (disk full, unwritable path) must
                # never fail the primary artifact or the capture rc
                traceback.print_exc()

    def run_section(self, name: str, fn):
        t0 = time.time()
        try:
            out = fn()
            # a section that reports its own elapsed_s (e2e: the best
            # run's bind time — the quantity pods_per_sec derives from)
            # keeps it; the section's wall share of the capture budget
            # is recorded separately either way
            out.setdefault("elapsed_s", round(time.time() - t0, 2))
            out["section_elapsed_s"] = round(time.time() - t0, 2)
            out.setdefault("status", "ok")
        except Exception:
            out = {"status": "error",
                   "elapsed_s": round(time.time() - t0, 2),
                   "tail": traceback.format_exc()[-600:]}
        self.doc["sections"][name] = out
        self.flush()
        return out


def _section_platform() -> dict:
    import jax
    devs = jax.devices()
    return {"backend": jax.default_backend(),
            "devices": [str(d) for d in devs],
            "n_devices": len(devs)}


def _section_dispatch() -> dict:
    """Roundtrip latency of a tiny dispatch+fetch, and device_put
    bandwidth — the two numbers the tile pipeline is designed around."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x.sum())
    x = jnp.ones(8)
    f(x).block_until_ready()  # warm
    lat = []
    for _ in range(30):
        t0 = time.perf_counter()
        float(f(x))
        lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()
    host = np.ones((64, 1024, 1024), np.float32)  # 256 MiB
    t0 = time.perf_counter()
    jax.device_put(host).block_until_ready()
    put_s = time.perf_counter() - t0
    return {"roundtrip_ms": {"p50": round(lat[len(lat) // 2], 2),
                             "p90": round(lat[int(len(lat) * 0.9)], 2),
                             "min": round(lat[0], 2)},
            "device_put_mb_per_s": round(host.nbytes / 2 ** 20 / put_s, 1)}


def _section_engine() -> dict:
    """Engine-only scoring throughput, the number three rounds of
    cpu-fallback benches could never attribute to hardware."""
    import bench  # repo-root module; watcher runs with cwd=/root/repo
    out = {}
    for n_nodes, n_pods in ((1000, 3000), (5000, 30000)):
        rate, bound = bench.engine_only(n_nodes, n_pods)
        out[f"{n_nodes}x{n_pods}"] = {
            "pods_per_sec": round(rate, 1), "bound": bound}
    return out


def _section_engine_spec() -> dict:
    """Node-local-tier A/B: speculative parallel-assign + conflict
    repair vs the sequential scan (SURVEY.md section 7 step 4's two
    branches, head to head). This is the tier the live e2e pipeline
    actually runs — its bench pods carry no services/RCs — so the
    winner here is what the north-star batch pays per pod."""
    import bench
    out = {}
    for n_nodes, n_pods in ((1000, 3000), (5000, 30000)):
        # plain = node-local tiers (the live e2e workload); spread =
        # the engine_only headline workload (one service), which the
        # speculative engine now also serves via the block-start-max
        # latch
        for tier, plain in (("plain", True), ("spread", False)):
            rec = {}
            for name, spec in (("scan", False), ("spec", True)):
                rate, bound = bench.engine_only(n_nodes, n_pods,
                                                plain=plain,
                                                speculative=spec)
                rec[name] = {"pods_per_sec": round(rate, 1),
                             "bound": bound}
            rec["winner"] = ("spec" if rec["spec"]["pods_per_sec"]
                             >= rec["scan"]["pods_per_sec"] else "scan")
            out[f"{n_nodes}x{n_pods}-{tier}"] = rec
    return out


_CPU_RATE_CACHE = "CPU_ENGINE_RATE.json"


def _cpu_engine_rates(repo: str) -> "dict | None":
    """Box-constant CPU engine rates at both bench shapes, measured
    once in a CPU-pinned subprocess and cached in the repo — NOT
    re-measured inside every capture's chip-lock window (a ~minutes
    CPU bench per hourly capture would starve the capture budget for
    a number that cannot change between captures)."""
    import subprocess
    path = os.path.join(repo, _CPU_RATE_CACHE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        pass
    code = ("import jax; jax.config.update('jax_platforms','cpu');"
            "import bench, json;"
            "small,_=bench.engine_only(1000,3000);"
            "big,_=bench.engine_only(5000,30000);"
            "print(json.dumps({'1000x3000': small, '5000x30000': big}))")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=900,
                          cwd=repo)
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            rates = json.loads(line)
            rates["ts"] = _utc()
            _atomic_write_json(path, rates)
            return rates
    return None


def _section_crossover(sections: dict) -> dict:
    """When does the TPU pay? (the r4 verdict's missing analysis:
    on-TPU e2e ran SLOWER than cpu-fallback.)

    The comparison is rate-vs-rate at each measured shape. No separate
    dispatch/transfer term is added: engine_only times run_chunked
    end-to-end from host numpy over the tunnel, so the TPU rate
    ALREADY embeds per-chunk host-to-device transfer and the blocking
    result fetch — it is the conservative in-situ device term (the
    live pipeline chains tile carries on-device, paying less). The
    host half of e2e is platform-identical, so whichever device term
    is smaller wins end-to-end."""
    eng = sections.get("engine") or {}
    if not (eng.get("5000x30000") or {}).get("pods_per_sec"):
        return {"status": "skipped", "reason": "needs engine section"}
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    cpu = _cpu_engine_rates(repo)
    if not cpu:
        return {"status": "error", "detail": "cpu reference bench failed"}
    out = {"cpu_rates_cached": _CPU_RATE_CACHE,
           "note": ("tpu rates embed tunnel dispatch + transfer "
                    "(run_chunked from host numpy); live pipeline "
                    "chains carries on-device and pays less"),
           "shapes": {}}
    wins = []
    for shape in ("1000x3000", "5000x30000"):
        tpu_rate = (eng.get(shape) or {}).get("pods_per_sec")
        cpu_rate = cpu.get(shape)
        if not tpu_rate or not cpu_rate:
            continue
        pods = int(shape.split("x")[1])
        rec = {"cpu_pods_per_sec": round(cpu_rate, 1),
               "tpu_pods_per_sec": tpu_rate,
               "cpu_device_term_s": round(pods / cpu_rate, 3),
               "tpu_device_term_s": round(pods / tpu_rate, 3),
               "tpu_wins": tpu_rate > cpu_rate}
        out["shapes"][shape] = rec
        wins.append((shape, rec["tpu_wins"]))
    out["verdict"] = ("; ".join(
        f"{s}: {'device wins' if w else 'cpu-fallback wins'}"
        for s, w in wins) or "no comparable shapes")
    return out


def _tiny_enc():
    from __graft_entry__ import _tiny_snapshot_inline

    from kubernetes_tpu.sched.device import encode_snapshot
    return encode_snapshot(_tiny_snapshot_inline(8, 16))


def _section_pallas() -> dict:
    """The predicate-filter kernel under real Mosaic + the latch test."""
    import numpy as np

    import jax

    from kubernetes_tpu.sched.device import BatchEngine, pallas_filter

    out: dict = {"backend": jax.default_backend()}
    enc = _tiny_enc()
    if not pallas_filter.supports(enc):
        return {"status": "error", "tail": "tiny encoding unsupported"}
    eng = BatchEngine()
    ref_mask, _ = eng.probe(enc)
    ref = np.asarray(ref_mask[:enc.n_pods]).astype(bool)

    # 1) real Mosaic compile + run (interpret=False on the tpu backend)
    masks = pallas_filter.filter_masks(enc)
    out["mosaic_parity"] = bool(np.array_equal(np.asarray(masks), ref))
    out["interpret"] = jax.default_backend() not in ("tpu",)

    # 2) forced rejection: swap in a kernel the Pallas TPU lowering
    # cannot handle (argsort has no Mosaic lowering rule) and prove a
    # REAL rejection propagates as a catchable exception through
    # BatchEngine.filter_masks, engages _pallas_broken, and still
    # returns the XLA answer
    import functools

    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def _bad_call(node_args, state_args, pod_args, interpret=False):
        def bad_kernel(x_ref, o_ref):
            o_ref[:] = jnp.argsort(x_ref[:], axis=-1).astype(jnp.int32)

        x = jnp.ones((8, 128), jnp.float32)
        return pl.pallas_call(
            bad_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
            interpret=False)(x)

    orig = pallas_filter._filter_call
    try:
        # confirm the bad kernel raises on its own (a real rejection)
        try:
            _bad_call(None, None, None)
            out["rejection_raised"] = False
        except Exception as e:
            out["rejection_raised"] = True
            out["rejection_type"] = type(e).__name__
            out["rejection_msg"] = str(e)[:200]
        pallas_filter._filter_call = _bad_call
        BatchEngine._pallas_broken = False
        fb = eng.filter_masks(enc)
        out["latch_engaged"] = bool(BatchEngine._pallas_broken)
        out["latch_fallback_parity"] = bool(np.array_equal(
            np.asarray(fb), ref))
    finally:
        pallas_filter._filter_call = orig
        BatchEngine._pallas_broken = False
    return out


def _section_e2e() -> dict:
    """Best of two runs: the tunneled chip adds ~70ms per fetch and the
    shared host shows ±20% run-to-run noise (same rationale as bench.py's
    headline best-of-2); both raw numbers are recorded."""
    from kubernetes_tpu.kubemark.benchmark import run_scheduling_benchmark
    runs = []
    for _ in range(2):
        r = run_scheduling_benchmark(5000, 30000, "batch")
        runs.append(r)
    best = max(runs, key=lambda r: r.pods_per_sec)
    return {"pods_per_sec": round(best.pods_per_sec, 1),
            "elapsed_s": round(best.elapsed_s, 2),
            "runs_pods_per_sec": [round(r.pods_per_sec, 1) for r in runs],
            "scheduled": best.scheduled, "nodes": best.n_nodes,
            "pods": best.n_pods}


def merge_best(doc: dict, best_path: str) -> None:
    """Fold one capture into the running per-section BEST artifact.

    The freshest capture (TPU_EVIDENCE.json) is the honest
    "this is what the hardware did last time we touched it" record, but
    on a tunneled, shared chip single captures swing ±2x; the best file
    records the demonstrated ceiling, every entry stamped with the
    capture timestamp it came from so the two are auditable together.
    """
    ts = doc.get("ts_start", _utc())
    try:
        with open(best_path) as f:
            best = json.load(f)
    except (OSError, ValueError):
        best = {"sections": {}}
    bs = best.setdefault("sections", {})
    secs = doc.get("sections", {})

    changed = False

    def _ok(name):
        s = secs.get(name)
        return s if s and s.get("status") == "ok" else None

    eng = _ok("engine")
    if eng:
        tgt = bs.setdefault("engine", {})
        for shape, rec in eng.items():
            if not isinstance(rec, dict) or "pods_per_sec" not in rec:
                continue
            old = tgt.get(shape)
            if old is None or rec["pods_per_sec"] > old["pods_per_sec"]:
                tgt[shape] = dict(rec, ts=ts)
                changed = True
    spec_ab = _ok("engine_spec")
    if spec_ab:
        tgt = bs.setdefault("engine_spec", {})
        for shape, rec in spec_ab.items():
            if not isinstance(rec, dict) or "scan" not in rec:
                continue
            old = tgt.get(shape)
            merged = {}
            for eng_name in ("scan", "spec"):
                new_e = rec.get(eng_name) or {}
                old_e = (old or {}).get(eng_name) or {}
                merged[eng_name] = (dict(new_e, ts=ts)
                                    if new_e.get("pods_per_sec", -1)
                                    > old_e.get("pods_per_sec", -1)
                                    else old_e)
            merged["winner"] = ("spec"
                                if merged["spec"].get("pods_per_sec", -1)
                                >= merged["scan"].get("pods_per_sec", -1)
                                else "scan")
            if merged != old:
                tgt[shape] = merged
                changed = True
    e2e = _ok("e2e")
    if e2e:
        old = bs.get("e2e")
        if old is None or e2e["pods_per_sec"] > old["pods_per_sec"]:
            bs["e2e"] = dict(e2e, ts=ts)
            changed = True
    disp = _ok("dispatch")
    if disp:
        old = bs.get("dispatch")
        if (old is None or disp["roundtrip_ms"]["p50"]
                < old["roundtrip_ms"]["p50"]):
            bs["dispatch"] = dict(disp, ts=ts)
            changed = True
    def _content(rec):
        # per-capture jitter fields must not count as a content change
        # (they would bump ts_updated — the best_stale signal — on
        # every capture)
        return {k: v for k, v in (rec or {}).items()
                if k not in ("ts", "elapsed_s", "section_elapsed_s",
                             "status")}

    if _ok("platform") and _content(bs.get("platform")) != _content(
            secs["platform"]):
        bs["platform"] = dict(secs["platform"], ts=ts)
        changed = True
    pal = _ok("pallas")
    if pal:
        # a flaky-chip run can return status ok with the validation bits
        # False; never let it replace a record that actually validated
        def _quality(rec):
            return (bool(rec.get("mosaic_parity")),
                    bool(rec.get("latch_fallback_parity")),
                    bool(rec.get("rejection_raised")))
        old = bs.get("pallas")
        # per-field non-regression, not lexicographic: a capture that
        # improves an earlier bit but regresses a later one must not
        # replace a fully-validated record
        if (old is None or all(n >= o for n, o in zip(_quality(pal),
                                                      _quality(old)))) \
                and _content(old) != _content(pal):
            bs["pallas"] = dict(pal, ts=ts)
            changed = True
    if changed:
        best["ts_updated"] = _utc()
        _atomic_write_json(best_path, best)


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="TPU_EVIDENCE.json")
    ap.add_argument("--best-out", default="TPU_EVIDENCE_BEST.json")
    ap.add_argument("--skip-e2e", action="store_true")
    args = ap.parse_args()

    ev = _Evidence(args.out, best_path=args.best_out)
    ev.run_section("platform", _section_platform)
    ev.run_section("dispatch", _section_dispatch)
    ev.run_section("pallas", _section_pallas)
    ev.run_section("engine", _section_engine)
    if not args.skip_e2e:
        ev.run_section("e2e", _section_e2e)
    # diagnostics last: these must never eat the headline sections'
    # share of the watcher's capture budget
    ev.run_section("crossover",
                   lambda: _section_crossover(ev.doc["sections"]))
    ev.run_section("engine_spec", _section_engine_spec)
    ev.doc["complete"] = True
    ev.doc["ts_end"] = _utc()
    ev.flush()
    print(json.dumps({k: v.get("status") for k, v in
                      ev.doc["sections"].items()}))


if __name__ == "__main__":
    main()
