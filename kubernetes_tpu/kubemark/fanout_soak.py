"""The 10k-watcher fan-out soak: a create-storm against the N-worker
serving plane (Fleet serving, README), with delivery lag gated as a
burn-rate SLO.

What the reference's watch cache buys (pkg/storage/cacher.go): one
apiserver process absorbs list/watch fan-out so etcd never sees
per-client load. This harness measures our horizontally-scaled version
of that promise — N apiserver workers over ONE shared store, each
worker's fan-out shard draining the publish ring independently — under
the load shape that actually hurts: thousands of concurrent watchers
on one resource while a committer storms creates into it.

Measurement is server-side, like kubemark/slo.py after the r3 verdict:
`watch_publish_deliver_lag_seconds` is observed by the shard drains
themselves (enqueue stamp -> fan-out hand-off), per {shard=...} label,
so a GIL-starved client thread cannot shrink the sample set. The
BurnRateEvaluator runs the pinned FLEET_SLOS (incl. the watch-deliver
SLO) over per-step fleet samples — the artifact bench.py writes
(SLO_10KWATCH.json) replays the alert timeline.

Scaling readout honesty (the PROFILE lesson): on a 1-core box the GIL
serializes the shard pumps, so wall-clock delivery throughput may not
scale 1 -> N workers. The harness records the ratio AND the
multi-consumer overlap witness (Store.drain_overlap: how often two
consumers were genuinely inside fan-out at once); when the box can't
show wall-clock scaling, the overlap readout is the gate and the
caveat is recorded in the artifact instead of a flattering number.
"""

from __future__ import annotations

import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from ..api.client import HttpClient
from ..api.registry import Registry
from ..api.server import ApiServerPool
from ..core.store import Store
from ..obs.metricsplane import (BurnRateEvaluator, FleetScraper,
                                RegistryTarget)
from ..utils.metrics import (APISERVER_WORKER_REQUESTS,
                             FANOUT_QUEUE_DEPTH_GAUGE,
                             WATCH_LAG_HISTOGRAM, MetricsRegistry)
from .benchmark import _bench_pod
from .slo import FLEET_SLOS, WATCH_DELIVER_SLO

#: the scaling acceptance bar (1 -> N workers) when wall-clock can
#: show it; below this the overlap witness gates instead
SCALING_RATIO_BAR = 1.5


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


@dataclass
class FanoutArm:
    """One storm run at a fixed worker count."""
    workers: int
    n_watchers: int
    creates_total: int
    elapsed_s: float
    create_pods_per_sec: float
    #: ring events consumed by the shard drains (summed across shards,
    #: so it grows with workers — informational, NOT the scaling basis)
    deliver_events_total: int
    #: per-watcher event deliveries per second (drained / elapsed) —
    #: the same total work in every arm, so the 1 -> N ratio of THIS
    #: number is the fair wall-clock scaling readout
    deliver_events_per_sec: float
    #: events the client side actually drained (sanity: == expected)
    drained_events_total: int
    drained_expected: int
    #: per-shard delivery stats: shard -> {watchers, delivered,
    #: lag_p50_ms, lag_p99_ms, queue_depth_max, worker_requests}
    per_worker: Dict[str, dict] = field(default_factory=dict)
    lag_p50_ms: float = 0.0
    lag_p99_ms: float = 0.0
    #: Store.drain_overlap() snapshot (multi-consumer witness)
    overlap: Dict = field(default_factory=dict)
    #: burn-rate alert timeline over the storm samples
    alerts: List[Dict] = field(default_factory=list)
    scrape_samples: int = 0
    http_events: int = 0
    watchers_alive_end: int = 0
    #: per-worker HTTP list sizes at storm end (each must equal
    #: creates_total: any worker serves the whole shared store)
    cross_worker_lists: List[int] = field(default_factory=list)

    @property
    def cross_worker_ok(self) -> bool:
        return all(n == self.creates_total
                   for n in self.cross_worker_lists)

    @property
    def delivered_ok(self) -> bool:
        """Exactly-once accounting: every watcher drained exactly the
        storm's event count — no drops, no dups, no stuck shard."""
        return self.drained_events_total == self.drained_expected

    @property
    def watch_slo_ok(self) -> bool:
        """The watch-deliver burn-rate SLO never stayed tripped: every
        TRIP has a CLEAR (transient storm lag is the expected shape;
        a stuck shard never clears)."""
        trips = [a for a in self.alerts
                 if a["slo"] == WATCH_DELIVER_SLO.name
                 and a["action"] == "TRIP"]
        clears = [a for a in self.alerts
                  if a["slo"] == WATCH_DELIVER_SLO.name
                  and a["action"] == "CLEAR"]
        return len(clears) >= len(trips)


@dataclass
class FanoutSoakResult:
    n_watchers: int
    workers: int
    storm_steps: int
    creates_per_step: int
    seed: int
    arm: FanoutArm
    #: the 1-worker arm of the same storm (compare_single=True runs)
    baseline: Optional[FanoutArm] = None
    scaling_ratio: float = 0.0
    #: 'wallclock' when the ratio met the bar, 'overlap' when the
    #: 1-core caveat applied and the overlap witness gated instead
    scaling_gate: str = ""
    scaling_ok: bool = False
    caveat: str = ""

    @property
    def ok(self) -> bool:
        return bool(self.arm.delivered_ok and self.arm.watch_slo_ok
                    and self.arm.cross_worker_ok
                    and (self.baseline is None or self.scaling_ok))

    def as_dict(self) -> Dict:
        d = asdict(self)
        d["ok"] = self.ok
        for key, arm in (("arm", self.arm), ("baseline", self.baseline)):
            if arm is None:
                continue
            d[key]["delivered_ok"] = arm.delivered_ok
            d[key]["watch_slo_ok"] = arm.watch_slo_ok
            d[key]["cross_worker_ok"] = arm.cross_worker_ok
        return d


def _run_arm(n_watchers: int, workers: int, storm_steps: int,
             creates_per_step: int, batch: int, seed: int,
             http_watchers: int, settle_timeout_s: float,
             name_base: int) -> FanoutArm:
    """One complete storm at a fixed worker count: fresh store, fresh
    pool, fresh metrics (no cross-arm mixing)."""
    metrics = MetricsRegistry()
    store = Store(metrics=metrics)
    registry = Registry(store)
    pool = ApiServerPool(registry, n_workers=workers,
                         metrics=metrics).start()
    scraper = FleetScraper([RegistryTarget("fleet", metrics)],
                           seed=seed)
    evaluator = BurnRateEvaluator(list(FLEET_SLOS))

    prefix = registry.prefix("pods", "default")
    shards = pool.shards()

    # ---- in-proc watchers, round-robin across worker shards ("from
    # now": the storm is the signal, replay would just add noise)
    watchers: List[List] = [[] for _ in pool.workers]
    for i in range(n_watchers):
        wi = i % len(pool.workers)
        w = registry.watch("pods", "default",
                           shard=pool.workers[wi]._shard)
        watchers[wi].append(w)

    # ---- a few real HTTP watch streams for wire realism (chunked
    # encoding, serialization, the works) — small on purpose; the
    # 10k-scale load is the in-proc fan-out above
    http_streams = []
    for i in range(http_watchers):
        c = HttpClient(pool.workers[i % len(pool.workers)].url)
        http_streams.append(c.watch("pods", namespace="default"))
    http_counts = [0] * len(http_streams)
    stop_http = threading.Event()

    def _http_drain(idx: int) -> None:
        while not stop_http.is_set():
            ev = http_streams[idx].next(timeout=0.2)
            if ev is not None and ev.type != "ERROR":
                http_counts[idx] += 1

    http_threads = [threading.Thread(target=_http_drain, args=(i,),
                                     daemon=True,
                                     name=f"fanout-http-{i}")
                    for i in range(len(http_streams))]
    for t in http_threads:
        t.start()

    # ---- client-side drainers: one per worker, bulk-draining that
    # worker's watchers (take_all = one lock hold per backlog)
    drained = [0] * len(pool.workers)
    stop_drain = threading.Event()

    def _drainer(wi: int) -> None:
        mine = watchers[wi]
        while True:
            got = 0
            for w in mine:
                got += len(w.take_all())
            drained[wi] += got
            if stop_drain.is_set() and got == 0:
                return
            if got == 0:
                time.sleep(0.002)

    drain_threads = [threading.Thread(target=_drainer, args=(wi,),
                                      daemon=True,
                                      name=f"fanout-drain-{wi}")
                     for wi in range(len(pool.workers))]
    for t in drain_threads:
        t.start()

    # ---- the create storm, sampled per step on the step axis
    creates_total = 0
    t0 = time.monotonic()
    try:
        for step in range(storm_steps):
            base = name_base + step * creates_per_step
            for off in range(0, creates_per_step, batch):
                n = min(batch, creates_per_step - off)
                entries = [(f"{prefix}bench-pod-{base + off + k:06d}",
                            _bench_pod(base + off + k), None)
                           for k in range(n)]
                store.create_batch(entries)
                creates_total += n
            # let the shard pumps catch this step's entries up before
            # sampling, so the step's lag observations are complete
            deadline = time.monotonic() + settle_timeout_s
            while any(sh.pending() > 0 for sh in shards) \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            evaluator.observe(scraper.sample(t=float(step)))
        elapsed = time.monotonic() - t0

        # drain samples so a trailing TRIP gets its CLEAR edge
        for extra in range(1, 9):
            evaluator.observe(scraper.sample(t=float(storm_steps - 1
                                                     + extra)))

        # ---- teardown order matters: stop the client drainers LAST,
        # after delivery quiesced, so drained == delivered is a real
        # accounting identity
        deadline = time.monotonic() + settle_timeout_s
        while any(sh.pending() > 0 for sh in shards) \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        stop_drain.set()
        for t in drain_threads:
            t.join(timeout=10.0)
        stop_http.set()
        for t in http_threads:
            t.join(timeout=5.0)
        # close the HTTP streams NOW (not in the finally) so their
        # server-side handlers exit and land the per-worker request
        # counter before the readout below
        for s in http_streams:
            try:
                s.stop()
            except Exception:
                pass
        if http_streams:
            time.sleep(0.2)

        # cross-worker read sanity: ANY worker serves the shared store,
        # so one HTTP list per worker must see every storm pod — this
        # also lands apiserver_worker_requests under each worker label
        list_counts = []
        for w in pool.workers:
            items, _rev = HttpClient(w.url).list("pods",
                                                 namespace="default")
            list_counts.append(len(items))

        # ---- readout
        per_worker: Dict[str, dict] = {}
        lag_all: List[float] = []
        for labels, stats in metrics.summary_stats(
                WATCH_LAG_HISTOGRAM).items():
            shard_name = dict(labels).get("shard")
            if shard_name is None:
                continue  # the default shard's unlabeled path
            per_worker[shard_name] = {
                "lag_p50_ms": round(stats["p50"] * 1e3, 3),
                "lag_p99_ms": round(stats["p99"] * 1e3, 3),
                "lag_samples": stats["count"]}
        for labels, samples in metrics.summary_samples(
                WATCH_LAG_HISTOGRAM).items():
            if dict(labels).get("shard") is not None:
                lag_all.extend(samples)
        lag_all.sort()
        for wi, sh in enumerate(shards):
            d = per_worker.setdefault(sh.name, {})
            d["watchers"] = len(watchers[wi])
            d["delivered"] = sh.delivered_events
            d["queue_depth_last"] = metrics.gauge(
                FANOUT_QUEUE_DEPTH_GAUGE, {"shard": sh.name})
            d["worker_requests"] = metrics.counter(
                APISERVER_WORKER_REQUESTS, {"worker": str(wi)})
        delivered_total = sum(sh.delivered_events for sh in shards)
        alive = store.watcher_count()

        return FanoutArm(
            workers=workers, n_watchers=n_watchers,
            creates_total=creates_total,
            elapsed_s=round(elapsed, 3),
            create_pods_per_sec=round(creates_total / max(1e-9, elapsed),
                                      1),
            deliver_events_total=delivered_total,
            deliver_events_per_sec=round(
                sum(drained) / max(1e-9, elapsed), 1),
            drained_events_total=sum(drained),
            drained_expected=creates_total * n_watchers,
            per_worker=per_worker,
            lag_p50_ms=round(_percentile(lag_all, 0.50) * 1e3, 3),
            lag_p99_ms=round(_percentile(lag_all, 0.99) * 1e3, 3),
            overlap=store.drain_overlap(),
            alerts=evaluator.events_dict(),
            scrape_samples=len(scraper.series()),
            http_events=sum(http_counts),
            watchers_alive_end=alive,
            cross_worker_lists=list_counts)
    finally:
        stop_drain.set()
        stop_http.set()
        for s in http_streams:
            try:
                s.stop()
            except Exception:
                pass
        pool.stop()


def run_fanout_soak(n_watchers: int = 10_000, workers: int = 4,
                    storm_steps: int = 10, creates_per_step: int = 200,
                    batch: int = 100, seed: int = 0,
                    http_watchers: int = 4,
                    settle_timeout_s: float = 30.0,
                    compare_single: bool = True) -> FanoutSoakResult:
    """The tentpole bench: an N-worker storm arm, optionally preceded
    by a 1-worker baseline arm of the SAME storm for the scaling
    readout. Fresh store/pool/metrics per arm — no cross-arm mixing.
    Deterministic inputs (pod names from a fixed base, samples on the
    step axis) so the SLO timeline in the artifact replays."""
    baseline = None
    if compare_single and workers > 1:
        baseline = _run_arm(n_watchers, 1, storm_steps,
                            creates_per_step, batch, seed,
                            http_watchers, settle_timeout_s,
                            name_base=0)
    arm = _run_arm(n_watchers, workers, storm_steps, creates_per_step,
                   batch, seed, http_watchers, settle_timeout_s,
                   name_base=0)

    result = FanoutSoakResult(
        n_watchers=n_watchers, workers=workers, storm_steps=storm_steps,
        creates_per_step=creates_per_step, seed=seed, arm=arm,
        baseline=baseline)
    if baseline is not None:
        ratio = (arm.deliver_events_per_sec
                 / max(1e-9, baseline.deliver_events_per_sec))
        result.scaling_ratio = round(ratio, 2)
        if ratio >= SCALING_RATIO_BAR:
            result.scaling_gate = "wallclock"
            result.scaling_ok = True
        else:
            # the honest 1-core path: the GIL serializes pump
            # wall-clock, so gate on the multi-consumer overlap
            # witness instead — were N consumers genuinely mid-fan-out
            # at once?
            ov = arm.overlap
            result.scaling_gate = "overlap"
            result.scaling_ok = bool(ov.get("max_concurrent", 0) >= 2
                                     and ov.get("overlapped", 0) > 0)
            result.caveat = (
                f"1-core GIL caveat: wall-clock delivery ratio "
                f"{result.scaling_ratio}x (bar {SCALING_RATIO_BAR}x) "
                f"not demonstrable on this box; gated on the "
                f"multi-consumer overlap witness instead "
                f"(max_concurrent={ov.get('max_concurrent')}, "
                f"overlap_frac={ov.get('overlap_frac')})")
    return result


def main() -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--watchers", type=int, default=10_000)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--creates-per-step", type=int, default=200)
    ap.add_argument("--no-baseline", action="store_true")
    args = ap.parse_args()
    r = run_fanout_soak(n_watchers=args.watchers, workers=args.workers,
                        storm_steps=args.steps,
                        creates_per_step=args.creates_per_step,
                        compare_single=not args.no_baseline)
    print(json.dumps({"metric": "fanout_soak", **r.as_dict()}))


if __name__ == "__main__":
    main()
