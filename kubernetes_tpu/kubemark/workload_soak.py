"""Trace-replay workload soak: a day of production under chaos, in
minutes, SLO-gated.

Stands up the full control plane over HTTP — registry + apiserver,
hollow fleet, batch scheduler, replication manager, deployment / job /
daemonset controllers, HPA, node-lifecycle controller — with every
component client behind the seeded API-fault injector, then replays a
`chaos.WorkloadPlan` trace tick by tick:

  diurnal   per-tick demand drives the HPA's metrics source; the HPA
            chases the sinusoid up and down through the Deployment's
            scale subresource (downscale damping keeps dips from
            flapping the fleet)
  burst     flash crowds of bare pods; their create->bind latency is
            the burst-window SLO population
  jobwave   batch Jobs created mid-replay; a hollow "executor" marks
            their Running pods Succeeded (or Failed for the drawn
            crash-looping waves, exercising the Job failure backoff)
  rollout   Deployment image bumps (hash rollout under the
            maxUnavailable invariant) and DaemonSet retargeting
  churn     Service create/delete against a fixed pool
  drain     low-priority batch fill waves + one high-priority surge
            (rides along inert here; `run_flash_drain_soak` below
            replays it alone with fleet-saturating requests — the
            priority-preemption acceptance scenario)

Optionally a seeded `NodeFaultPlan` hard-kills a fraction of the fleet
at `kill_tick` — the replay then proves the whole recovery chain under
live heterogeneous load.

SLO gates (the ISSUE-8 acceptance bar), read server-side where the
server is the authority (api latency summaries; registry state for
bindings):

  - burst bind p99 under `bind_p99_limit_s`
  - HPA convergence: tracking error vs the pure demand curve never
    stays out of tolerance longer than `hpa_max_lag_ticks` ticks
  - zero pods bound to dead nodes at quiesce, zero duplicate bindings
  - every non-failing Job Complete; the final Service set equal to the
    plan's pure fold
  - the applied event trace byte-identical to `plan.schedule()` (and
    the node-kill victim set to its plan) — same seed, same day

Determinism note: the replay clock is the COMPRESSED TICK axis
(`tick_wall_s` wall seconds per virtual tick), and the contract covers
WHAT happens at each tick, not wall timing. Final-state equality
between two same-seed invocations is asserted over `state_summary()` —
the canonical deterministic projection (service set, completed-job
set, DaemonSet coverage, crowd-pod bind totals, dead-node set, HPA
band membership). The HPA's 10% tolerance band admits more than one
integer fixed point, so raw replica counts are compared as
band-membership, not bit-equality (see DIVERGENCES.md).

Shared verbatim by the pytest gates (tests/test_workload.py) and the
bench arm (bench.py --workload-seed), so the artifact records exactly
the invariants the tests enforce.
"""

from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api.client import HttpClient, InProcClient
from ..api.registry import Registry
from ..api.server import ApiServer, ApiServerPool
from ..chaos import (ChaosClient, FaultPlan, NodeChaos, NodeFaultPlan,
                     WorkloadChaos, WorkloadPlan)
from ..controllers.daemon import DaemonSetController
from ..controllers.deployment import DeploymentController
from ..controllers.job import JobController
from ..controllers.node import NodeController
from ..controllers.podautoscaler import HorizontalController
from ..controllers.replication import ReplicationManager
from ..core import types as api
from ..core.quantity import parse_quantity
from ..obs import tracer as _obs_tracer
from ..obs.flightrec import FlightRecorder
from ..obs.metricsplane import (BurnRateEvaluator, FleetScraper,
                                HttpTarget, RegistryTarget)
from ..sched.batch import BatchScheduler
from ..sched.factory import ConfigFactory
from ..sched.preemption import PreemptionPass
from ..utils.clock import REAL, Clock
from ..utils.metrics import (APISERVER_LATENCY_SUMMARY, CROWD_COUNTERS,
                             MetricsRegistry, PREEMPTION_COUNTERS,
                             SURGE_BIND_HISTOGRAM, SURGE_COUNTERS,
                             global_metrics)
from .fleet import HollowFleet
from .slo import CROWD_BIND_SLO, FLEET_SLOS, SURGE_BIND_SLO

#: demand units one replica serves at exactly the HPA target — the
#: pure demand->replicas mapping the convergence gate compares against
UNITS_PER_REPLICA = 4
HPA_TARGET_PCT = 50
HPA_MAX_REPLICAS = 60

#: pod name of the watch-audit delivery barrier (never scheduled; its
#: ADDED is the only post-quiesce pods write — see the audit readout)
_AUDIT_SENTINEL = "watch-audit-sentinel"

#: pinned spelling (the metric-pinning lint contract)
LATENCY_METRIC = APISERVER_LATENCY_SUMMARY


def ideal_replicas(demand: int) -> int:
    """The unique HPA equilibrium for a demand level (pure)."""
    return max(1, min(HPA_MAX_REPLICAS, int(math.ceil(
        demand * 100.0 / (UNITS_PER_REPLICA * HPA_TARGET_PCT)))))


def hpa_in_band(demand: int, replicas: int) -> bool:
    """The HPA's own no-move region (its 10% utilization tolerance,
    plus sampling slack): the convergence gate must judge the
    controller by ITS fixed-point criterion — ceil rounding means more
    than one replica count can satisfy the band for one demand level,
    and all of them are converged (see module docstring). A fleet
    pegged at the min/max clamp while demand is beyond it is converged
    too — the controller has nothing left to move."""
    ideal = ideal_replicas(demand)
    if ideal >= HPA_MAX_REPLICAS and replicas >= HPA_MAX_REPLICAS:
        return True
    if ideal <= 1 and replicas <= 1:
        return True
    ratio = demand * 100.0 / (
        UNITS_PER_REPLICA * max(1, replicas)) / HPA_TARGET_PCT
    return abs(ratio - 1.0) <= 0.12


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


@dataclass
class WorkloadSoakResult:
    converged: bool
    n_nodes: int
    seed: int
    ticks: int
    #: applied workload trace == plan.schedule(), per generator
    schedule_replayed: bool = False
    #: applied node-kill victims == NodeFaultPlan replay
    node_schedule_replayed: bool = True
    events_applied: int = 0
    events_expected: int = 0
    killed: List[str] = field(default_factory=list)
    # ---- burst bind SLO (create -> spec.nodeName observed)
    bind_p50_s: float = 0.0
    bind_p99_s: float = 0.0
    bind_samples: int = 0
    bind_p99_limit_s: float = 3.0
    # ---- HPA convergence vs the pure demand curve
    hpa_max_lag_ticks: int = 0
    hpa_lag_limit_ticks: int = 0
    hpa_in_band_final: bool = False
    hpa_track: List[Tuple[int, int, int, int]] = field(
        default_factory=list)  # (tick, demand, ideal, actual)
    # ---- correctness gates
    duplicate_bindings: int = 0
    dead_bound: int = 0
    jobs_expected: int = 0
    jobs_completed: int = 0
    backoff_requeues: float = 0.0
    failing_waves: int = 0
    services_ok: bool = False
    services_final: List[str] = field(default_factory=list)
    # ---- per-phase bind throughput (replay split into thirds)
    phases: List[Dict] = field(default_factory=list)
    # ---- server-side API latency over the whole replay
    api_p99_ms: float = 0.0
    api_calls: int = 0
    # ---- metrics plane (scrape=True): per-tick fleet samples + the
    # burn-rate alert timeline (AlertEvent.to_dict list, in order)
    scrape_samples: int = 0
    scrape_resets: int = 0
    scrape_errors: int = 0
    alerts: List[Dict] = field(default_factory=list)
    alert_clear_limit_ticks: int = 6
    flight_bundles: List[str] = field(default_factory=list)
    #: the full FleetScraper export (keep_series=True runs only) —
    #: what bench.py --timeseries records and tools/obs_report.py
    #: renders; popped from as_dict() so the workload section stays
    #: verdict-sized
    scrape_export: Optional[Dict] = None
    # ---- Fleet serving (apiserver_workers > 0): the multi-worker
    # plane with rolling restarts mid-replay, audited by one watch
    # stream per worker shard + a default-shard truth stream
    apiserver_workers: int = 0
    worker_restarts: int = 0
    #: truth events a worker stream never delivered (must be 0)
    watch_audit_missed: int = 0
    #: events delivered twice within one registration past the resume
    #: floor — protocol duplicates, not resume replay (must be 0)
    watch_audit_dups: int = 0
    #: events a worker stream saw that truth never did (must be 0)
    watch_audit_extra: int = 0
    #: at-least-once resume artifacts (reflector-deduped, reported
    #: honestly: a DELETED tombstone carries the pre-delete rv, so a
    #: client resuming from its last-seen resourceVersion replays
    #: across a trailing delete — the reference has the same bias)
    watch_audit_redelivered: int = 0
    watch_audit_streams: int = 0
    #: the actual (stream, type, name, rv) records behind missed/extra
    #: — empty on a clean audit; kept so a failed gate names the
    #: events instead of just counting them
    watch_audit_diff: List = field(default_factory=list)
    detail: str = ""

    @property
    def bind_p99_ok(self) -> Optional[bool]:
        if self.bind_samples == 0:
            return None  # the plan drew no bursts: nothing to gate
        return self.bind_p99_s < self.bind_p99_limit_s

    @property
    def hpa_ok(self) -> bool:
        return (self.hpa_max_lag_ticks <= self.hpa_lag_limit_ticks
                and self.hpa_in_band_final)

    @property
    def alerts_ok(self) -> Optional[bool]:
        """The burn-rate alert gate (scrape=True runs only): every
        flash crowd must TRIP the crowd fast-burn alert — the crowd's
        pods cannot bind in the tick they land, so a crowd that does
        NOT trip means the alert pipeline is broken — and every TRIP
        must CLEAR within alert_clear_limit_ticks samples once binds
        drain. None when the plane was off or no crowd was drawn."""
        if self.scrape_samples == 0:
            return None
        crowd = [a for a in self.alerts
                 if a["slo"] == CROWD_BIND_SLO.name]
        if self.bind_samples == 0 and not crowd:
            return None  # the plan drew no bursts: nothing to gate
        trips = [a for a in crowd if a["action"] == "TRIP"]
        if self.bind_samples > 0 and not trips:
            return False
        for i, a in enumerate(crowd):
            if a["action"] != "TRIP":
                continue
            clear = next((b for b in crowd[i + 1:]
                          if b["action"] == "CLEAR"), None)
            if clear is None or (clear["sample"] - a["sample"]
                                 > self.alert_clear_limit_ticks):
                return False
        return True

    @property
    def watch_audit_ok(self) -> Optional[bool]:
        """The multi-worker watch contract (apiserver_workers runs
        only): every pods event the default-shard truth stream saw
        was delivered by every worker shard exactly once per
        registration — across rolling restarts — with no inventions.
        None when the pool was off."""
        if self.watch_audit_streams == 0:
            return None
        return (self.watch_audit_missed == 0
                and self.watch_audit_dups == 0
                and self.watch_audit_extra == 0)

    @property
    def slo_ok(self) -> bool:
        """Every gate at once — what the soak test asserts and the
        bench artifact records."""
        return bool(self.converged and self.schedule_replayed
                    and self.node_schedule_replayed
                    and self.bind_p99_ok is not False
                    and self.hpa_ok
                    and self.alerts_ok is not False
                    and self.watch_audit_ok is not False
                    and self.duplicate_bindings == 0
                    and self.dead_bound == 0
                    and self.jobs_completed >= self.jobs_expected
                    and self.services_ok)

    def state_summary(self) -> Dict:
        """The canonical deterministic projection of post-replay state
        — what two same-seed invocations are compared on (see module
        docstring for why HPA replicas are band-membership). The
        alert timeline (sample index, SLO, edge) is part of it: trip
        and clear ticks must replay."""
        return {
            "services": list(self.services_final),
            "jobs_completed": self.jobs_completed,
            "jobs_expected": self.jobs_expected,
            "crowd_bound": self.bind_samples,
            "killed": list(self.killed),
            "hpa_in_band_final": self.hpa_in_band_final,
            "converged": self.converged,
            "alerts": [[a["sample"], a["slo"], a["action"]]
                       for a in self.alerts],
        }

    def as_dict(self) -> Dict:
        d = asdict(self)
        d["bind_p99_ok"] = self.bind_p99_ok
        d["hpa_ok"] = self.hpa_ok
        d["alerts_ok"] = self.alerts_ok
        d["watch_audit_ok"] = self.watch_audit_ok
        d["slo_ok"] = self.slo_ok
        d["hpa_track"] = [list(t) for t in self.hpa_track]
        d.pop("scrape_export", None)
        return d


def run_workload_soak(n_nodes: int = 12, seed: int = 0,
                      plan: Optional[WorkloadPlan] = None,
                      tick_wall_s: float = 0.4,
                      fault_rate: float = 0.05,
                      node_kill_fraction: float = 0.0,
                      kill_tick: Optional[int] = None,
                      bind_p99_limit_s: float = 3.0,
                      hpa_damping_ticks: int = 2,
                      hpa_lag_limit_ticks: Optional[int] = None,
                      timeout: float = 180.0,
                      heartbeat_interval: float = 0.5,
                      monitor_period: float = 0.1,
                      monitor_grace_period: float = 1.5,
                      pod_eviction_timeout: float = 0.3,
                      registry: Optional[Registry] = None,
                      clock: Optional[Clock] = None,
                      scrape: bool = False,
                      alert_clear_limit_ticks: int = 6,
                      keep_series: bool = False,
                      flight_dir: Optional[str] = None,
                      apiserver_workers: int = 0,
                      worker_restarts: bool = True
                      ) -> WorkloadSoakResult:
    """One seeded trace replay; see the module docstring for the
    scenario. Timing knobs default to soak-compressed values.

    scrape=True turns on the metrics plane: a FleetScraper pulls the
    apiserver's /metrics over HTTP (through the shed-exempt path) and
    the in-proc fleet registry once per tick, and a BurnRateEvaluator
    runs the pinned FLEET_SLOS over the samples — the crowd fast-burn
    alert timeline becomes a gate (alerts_ok). flight_dir additionally
    arms a FlightRecorder: SLO trips and node-kill chaos dump
    post-mortem bundles there.

    apiserver_workers > 0 replaces the single apiserver with an
    ApiServerPool of that many workers over the shared store (Fleet
    serving). Chaos traffic and the scraper ride worker 0 (its port
    survives restarts); one audit watch stream per worker shard plus
    a default-shard truth stream gate the watch contract
    (watch_audit_ok). worker_restarts additionally bounces one worker
    at each quarter-point tick — the rolling-restart chaos the
    acceptance replay runs."""
    clock = clock or REAL
    plan = plan or WorkloadPlan(seed=seed)
    seed = plan.seed
    fault_plan = FaultPlan(seed=seed, error_rate=fault_rate)
    node_plan = NodeFaultPlan(seed=seed, kill_fraction=node_kill_fraction)
    kill_tick = (plan.ticks // 2 if kill_tick is None else kill_tick)
    # damping intentionally holds downscales for hpa_damping_ticks; the
    # +6 absorbs fault-delayed reconciles without unbounding the gate
    hpa_lag_limit = (hpa_damping_ticks + 6 if hpa_lag_limit_ticks is None
                     else hpa_lag_limit_ticks)

    metrics = MetricsRegistry()
    registry = registry or Registry()
    pool = None
    if apiserver_workers > 0:
        pool = ApiServerPool(registry, n_workers=apiserver_workers,
                             metrics=metrics).start()
        server = pool.workers[0]
    else:
        server = ApiServer(registry, port=0, metrics=metrics).start()
    chaos = ChaosClient(HttpClient(server.url), fault_plan)
    inproc = InProcClient(registry)

    result = WorkloadSoakResult(
        converged=False, n_nodes=n_nodes, seed=seed, ticks=plan.ticks,
        bind_p99_limit_s=bind_p99_limit_s,
        hpa_lag_limit_ticks=hpa_lag_limit,
        alert_clear_limit_ticks=alert_clear_limit_ticks,
        apiserver_workers=apiserver_workers)

    # ---- metrics plane: scraper + burn-rate evaluator + recorder
    recorder = (FlightRecorder(flight_dir, clock=clock)
                if flight_dir else None)
    tick_now = [0]  # current replay tick, for bundle metadata
    sampled_tick = [-1]  # last tick sampled in-crowd (see _on_crowd)

    def _on_trip(ev):
        if recorder is not None:
            recorder.dump(f"slo-{ev.slo}", scraper=scraper,
                          tracer=_obs_tracer(),
                          chaos={"tick": tick_now[0]},
                          extra=ev.to_dict())

    scraper = evaluator = None
    if scrape:
        scraper = FleetScraper(
            [HttpTarget("apiserver", server.url + "/metrics"),
             RegistryTarget("fleet", global_metrics)],
            clock=clock, cadence_s=tick_wall_s, seed=seed)
        evaluator = BurnRateEvaluator(list(FLEET_SLOS),
                                      on_trip=_on_trip)
    sched_pure = plan.schedule()
    result.events_expected = sum(len(v) for v in sched_pure.values())
    backoff_base = global_metrics.counter_sum("job_backoff_requeues_total")

    # ---- the fleet, zoned for DaemonSet retargeting
    fleet = HollowFleet(
        chaos, n_nodes, heartbeat_interval=heartbeat_interval,
        labels_for=lambda i: {"zone": f"z{i % plan.n_zones}"},
        jitter_seed=seed).run()
    factory = ConfigFactory(chaos, rate_limit=False).start()
    sched = BatchScheduler(factory.create_batch()).run()
    rc_mgr = ReplicationManager(chaos).run()
    deploy_ctl = DeploymentController(chaos).run()
    job_ctl = JobController(chaos, failure_backoff_initial=0.2,
                            failure_backoff_cap=2.0).run()
    ds_ctl = DaemonSetController(chaos).run()
    node_ctl = NodeController(
        chaos, monitor_period=monitor_period,
        monitor_grace_period=monitor_grace_period,
        pod_eviction_timeout=pod_eviction_timeout,
        eviction_qps=1000.0, eviction_burst=1000).run()

    wl = WorkloadChaos(chaos, plan, clock=clock)
    node_chaos = NodeChaos(fleet, node_plan)

    # ---- HPA rides the shared demand signal: utilization is demand
    # over serving capacity, so the equilibrium is exactly
    # ideal_replicas(demand) and the convergence gate is pure
    def metrics_source(ns, selector):
        try:
            d = registry.get("deployments", plan.deployment, "default")
        except Exception:
            return None
        cur = max(1, d.spec.replicas)
        return 100.0 * wl.demand / (UNITS_PER_REPLICA * cur)

    hpa_ctl = HorizontalController(
        chaos, metrics_source, sync_period=max(0.05, tick_wall_s / 3.0),
        downscale_stabilization=hpa_damping_ticks * tick_wall_s).run()

    # ---- trackers ride the live registry directly (no chaos, no HTTP)
    lock = threading.Lock()
    bound_to: Dict[str, str] = {}            # pod uid -> node
    duplicates: List[Tuple[str, str, str]] = []
    crowd_created: Dict[str, float] = {}
    crowd_tick: Dict[str, int] = {}          # pod name -> landing tick
    crowd_bound: Dict[str, float] = {}
    bind_stamps: List[float] = []            # all binds, for phases
    stop_threads = threading.Event()

    def _on_crowd(names):
        # synchronous with apply_tick: the created counter moves in
        # the SAME tick the crowd lands, so the burn-rate evaluator's
        # sample at this tick deterministically sees the error ratio
        # spike (the pods cannot have bound yet)
        crowd_created.update({n: time.monotonic() for n in names})
        crowd_tick.update({n: tick_now[0] for n in names})
        metrics.inc(CROWD_COUNTERS[0], by=float(len(names)))
        # take THIS tick's sample right here, synchronously after the
        # created counter moved: the scheduler cannot have bound any
        # of the crowd yet, so the sample deterministically shows the
        # whole crowd outstanding and the TRIP edge replays — scraping
        # later from the tick loop races the binder (a slow apply_tick
        # under multi-worker contention let fast binds erase the TRIP)
        if scraper is not None and sampled_tick[0] != tick_now[0]:
            sampled_tick[0] = tick_now[0]
            evaluator.observe(scraper.sample(t=float(tick_now[0])))

    wl.on_crowd = _on_crowd

    def tracker():
        # one registry sweep: duplicate-binding ledger + crowd bind
        # stamps (server-side truth — spec.nodeName in the store)
        while not stop_threads.is_set():
            try:
                pods, _ = registry.list("pods", "default")
            except Exception:
                time.sleep(0.03)
                continue
            now = time.monotonic()
            with lock:
                for p in pods:
                    node = p.spec.node_name
                    if not node:
                        continue
                    prev = bound_to.get(p.metadata.uid)
                    if prev is not None and prev != node:
                        duplicates.append((p.metadata.uid, prev, node))
                    if prev is None:
                        bind_stamps.append(now)
                    bound_to[p.metadata.uid] = node
                    name = p.metadata.name
                    if (name.startswith("crowd-")
                            and name not in crowd_bound):
                        crowd_bound[name] = now
                        metrics.inc(CROWD_COUNTERS[1])
            time.sleep(0.03)

    def executor():
        # the hollow workload side: Running job pods exit — cleanly for
        # normal waves, crashing for the drawn failing waves
        from dataclasses import replace
        while not stop_threads.is_set():
            try:
                pods, _ = registry.list("pods", "default")
            except Exception:
                time.sleep(0.05)
                continue
            for p in pods:
                wave = p.metadata.labels.get("wave")
                if not wave or p.status.phase != "Running":
                    continue
                _, failing = wl.jobs.get(wave, (0, False))
                phase = "Failed" if failing else "Succeeded"
                try:
                    inproc.update_status("pods", replace(
                        p, status=replace(p.status, phase=phase)),
                        "default")
                except Exception:
                    pass  # conflict/NotFound: next sweep retries
            time.sleep(0.05)

    threads = [threading.Thread(target=tracker, daemon=True,
                                name="workload-tracker"),
               threading.Thread(target=executor, daemon=True,
                                name="workload-executor")]
    for t in threads:
        t.start()

    # ---- Fleet serving watch audit: one stream per worker shard plus
    # a default-shard truth stream, all watching pods since rev 0. A
    # restarted worker 410s its stream (ERROR), and the audit resumes
    # on the replacement shard from its last-seen resourceVersion —
    # exactly the re-list-and-re-watch loop a real client runs.
    audit_lock = threading.Lock()
    audit_states: List[dict] = []
    truth_st: dict = {}

    def _audit_drain(st: dict) -> None:
        for ev in st["watcher"]:
            if ev.type == "ERROR":
                return  # worker restarting: the tick loop re-registers
            o = ev.object
            rec = (ev.type, o.metadata.name,
                   int(o.metadata.resource_version))
            with audit_lock:
                if rec[1] == _AUDIT_SENTINEL:
                    # the readout's delivery barrier (see there):
                    # advances the frontier, excluded from the
                    # compared event sets
                    st["last"] = max(st["last"], rec[2])
                    continue
                if rec in st["seen"]:
                    if rec[2] <= st["floor"]:
                        # resume replay across a DELETED tail: the
                        # tombstone carries the pre-delete rv, so
                        # resuming from last-seen rv is at-least-once
                        # there — the reflector dedup every real
                        # client runs, reported but not gated
                        st["redelivered"] += 1
                    else:
                        st["dups"] += 1   # protocol duplicate: gates
                    continue
                st["seen"].add(rec)
                st["last"] = max(st["last"], rec[2])

    def _audit_register(st: dict, shard, since: int) -> None:
        st["floor"] = since
        st["watcher"] = registry.watch("pods", "default",
                                       since_rev=since, shard=shard)
        t = threading.Thread(target=_audit_drain, args=(st,),
                             daemon=True,
                             name=f"watch-audit-{st['name']}")
        st["thread"] = t
        t.start()

    def _audit_state(name: str) -> dict:
        return {"name": name, "seen": set(), "last": 0, "floor": 0,
                "dups": 0, "redelivered": 0, "watcher": None,
                "thread": None}

    restart_at: Dict[int, int] = {}
    if pool is not None:
        truth_st = _audit_state("truth")
        _audit_register(truth_st, None, 0)
        for i, wkr in enumerate(pool.workers):
            st = _audit_state(f"w{i}")
            _audit_register(st, wkr._shard, 0)
            audit_states.append(st)
        if worker_restarts and plan.ticks >= 8:
            # quarter-point ticks, round-robin victims: deterministic
            # restart schedule (same seed => same bounce timeline)
            for j, at in enumerate((plan.ticks // 4, plan.ticks // 2,
                                    (3 * plan.ticks) // 4)):
                restart_at[at] = j % apiserver_workers

    def wait_until(cond, deadline):
        while clock.monotonic() < deadline:
            if cond():
                return True
            clock.sleep(0.05)
        return cond()

    def retry_api(fn, deadline):
        while True:
            try:
                return fn()
            except Exception:
                if clock.monotonic() > deadline:
                    raise
                clock.sleep(0.05)

    try:
        deadline = clock.monotonic() + timeout
        if not wait_until(
                lambda: len(factory.node_lister.list()) >= n_nodes,
                deadline):
            result.detail = "fleet never registered"
            return result

        # warm the engine's compile cache at the run's shapes while the
        # scheduler is still idle (a live scheduler has warm caches; an
        # XLA compile inside the replay would bill seconds of compiler
        # time to the first burst's bind-latency SLO — the
        # kubemark/slo.py lesson)
        from .benchmark import _warmup_batch
        _warmup_batch(sched, factory)

        # ---- bootstrap the standing workload (retried through faults)
        base_replicas = ideal_replicas(plan.diurnal_base)
        tiny = api.PodSpec(containers=[api.Container(
            name="c", image="img:v1",
            resources=api.ResourceRequirements(
                requests={"cpu": parse_quantity("10m"),
                          "memory": parse_quantity("16Mi")}))])
        retry_api(lambda: chaos.create("deployments", api.Deployment(
            metadata=api.ObjectMeta(name=plan.deployment,
                                    namespace="default"),
            spec=api.DeploymentSpec(
                replicas=base_replicas,
                selector={"app": plan.deployment},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(
                        labels={"app": plan.deployment}),
                    spec=tiny))), "default"), deadline)
        retry_api(lambda: chaos.create(
            "horizontalpodautoscalers", api.HorizontalPodAutoscaler(
                metadata=api.ObjectMeta(name=f"{plan.deployment}-hpa",
                                        namespace="default"),
                spec=api.HorizontalPodAutoscalerSpec(
                    scale_ref=api.SubresourceReference(
                        kind="Deployment", name=plan.deployment,
                        namespace="default"),
                    min_replicas=1, max_replicas=HPA_MAX_REPLICAS,
                    cpu_utilization_target_percentage=HPA_TARGET_PCT)),
            "default"), deadline)
        retry_api(lambda: chaos.create("daemonsets", api.DaemonSet(
            metadata=api.ObjectMeta(name=plan.daemonset,
                                    namespace="default"),
            spec=api.DaemonSetSpec(
                selector={"ds": plan.daemonset},
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(
                        labels={"ds": plan.daemonset}),
                    spec=tiny))), "default"), deadline)

        def deployment_ready():
            try:
                d = registry.get("deployments", plan.deployment,
                                 "default")
            except Exception:
                return False
            return (d.status.available_replicas >= base_replicas
                    and d.status.unavailable_replicas == 0)

        if not wait_until(deployment_ready, deadline):
            result.detail = "bootstrap deployment never became available"
            return result

        # ---- the replay: one compressed tick at a time
        t_start = time.monotonic()
        dead: set = set()
        hpa_bad_run = 0
        for tick in range(plan.ticks):
            tick_now[0] = tick
            # crowds that landed on EARLIER ticks must be bound before
            # this tick's sample, or the CLEAR edge races the scrape
            # on a loaded box (a worker restart this tick makes the
            # race wall-clock-sized); bounded wait BEFORE the tick
            # applies so the in-crowd sample below also sees them
            # settled — a genuinely stuck bind path still reads as a
            # late clear and fails the alert-lag gate
            if scraper is not None:
                due = [n for n, t0 in crowd_tick.items() if t0 < tick]

                def _crowds_quiesced():
                    with lock:
                        return all(n in crowd_bound for n in due)

                # the cap must dominate a loaded box's bind latency
                # (~2s seen with 3 workers + restart on one core) or
                # the timeline goes non-deterministic again; in a
                # healthy run the wait returns in well under a tick
                wait_until(_crowds_quiesced,
                           clock.monotonic() + max(5.0,
                                                   4.0 * tick_wall_s))
            if tick in restart_at:
                # rolling worker restart (same port, fresh shard);
                # BEFORE this tick's scrape so the blip and the
                # re-registration land on a deterministic tick axis
                idx = restart_at[tick]
                pool.restart(idx)
                result.worker_restarts += 1
                st = audit_states[idx]
                if st["thread"] is not None:
                    st["thread"].join(timeout=5.0)  # exits on ERROR
                with audit_lock:
                    since = st["last"]
                _audit_register(st, pool.workers[idx]._shard, since)
            wl.apply_tick(tick, deadline)
            if node_kill_fraction > 0 and tick == kill_tick:
                result.killed = node_chaos.kill()
                dead = set(result.killed)
                result.node_schedule_replayed = (
                    result.killed
                    == node_plan.schedule(fleet.node_names())["kill"])
                if recorder is not None:
                    recorder.dump("chaos-node-kill", scraper=scraper,
                                  tracer=_obs_tracer(),
                                  chaos={"tick": tick,
                                         "victims": result.killed})
            # scrape ON the tick axis, right after the tick's events
            # applied: the sample index IS the tick, so the alert
            # timeline replays across same-seed runs (a crowd tick
            # already took its sample inside _on_crowd — see there)
            if scraper is not None and sampled_tick[0] != tick:
                evaluator.observe(scraper.sample(t=float(tick)))
            time.sleep(tick_wall_s)
            # HPA tracking sample, against the pure curve
            try:
                d = registry.get("deployments", plan.deployment,
                                 "default")
                actual = d.spec.replicas
            except Exception:
                actual = -1
            ideal = ideal_replicas(wl.demand)
            result.hpa_track.append((tick, wl.demand, ideal, actual))
            in_band = actual > 0 and hpa_in_band(wl.demand, actual)
            # damping holds downscales for hpa_damping_ticks by design:
            # only count ticks beyond the window as lag
            hpa_bad_run = 0 if in_band else hpa_bad_run + 1
            lag = max(0, hpa_bad_run - hpa_damping_ticks)
            result.hpa_max_lag_ticks = max(result.hpa_max_lag_ticks, lag)
        t_end = time.monotonic()

        # ---- quiesce: every workload class settled on live nodes
        expected_services = plan.expected_services()
        result.jobs_expected = sum(
            1 for _n, (_c, failing) in wl.jobs.items() if not failing)
        result.failing_waves = sum(
            1 for _n, (_c, failing) in wl.jobs.items() if failing)

        def completed_jobs():
            try:
                jobs, _ = registry.list("jobs", "default")
            except Exception:
                return -1
            return sum(1 for j in jobs
                       if any(c.type == "Complete" and c.status == "True"
                              for c in j.status.conditions))

        def services_now():
            try:
                svcs, _ = registry.list("services", "default")
            except Exception:
                return None
            return sorted(s.metadata.name for s in svcs
                          if s.metadata.deletion_timestamp is None)

        def crowd_settled():
            # every crowd pod observed bound (the flash crowd was
            # served); pods later evicted off killed nodes still count
            # — they were served before the node died
            with lock:
                return len(crowd_bound) >= len(wl.crowd_pods)

        def hpa_settled():
            try:
                d = registry.get("deployments", plan.deployment,
                                 "default")
            except Exception:
                return False
            return (hpa_in_band(wl.demand, d.spec.replicas)
                    and d.status.unavailable_replicas == 0)

        def dead_bound_count():
            try:
                pods, _ = registry.list("pods", "default")
            except Exception:
                return -1
            return sum(1 for p in pods if p.spec.node_name in dead)

        def quiesced():
            return (crowd_settled()
                    and completed_jobs() >= result.jobs_expected
                    and services_now() == expected_services
                    and hpa_settled()
                    and dead_bound_count() == 0)

        ok = wait_until(quiesced, deadline)
        result.converged = ok
        # drain samples past the replay: a crowd landing on the final
        # ticks must still get its CLEAR edge once binds settle (the
        # quiesce wait above ensures they have)
        if scraper is not None:
            for extra in range(3):
                evaluator.observe(
                    scraper.sample(t=float(plan.ticks + extra)))
            result.scrape_samples = len(scraper.series())
            result.scrape_resets = scraper.resets_total
            result.scrape_errors = scraper.errors_total
            result.alerts = evaluator.events_dict()
            if keep_series:
                result.scrape_export = json.loads(scraper.export_json())
        if recorder is not None:
            result.flight_bundles = list(recorder.bundles)

        # ---- Fleet serving watch audit readout: the system is
        # quiesced (no pods writes in flight), so after a short
        # pump-settle every worker stream must hold exactly the truth
        # stream's event set
        if pool is not None:
            # Delivery barrier: frontier comparisons alone CANNOT see a
            # trailing DELETE — its tombstone carries the pre-delete
            # rv, so it advances no stream's `last`, and a worker pump
            # still holding the final delete batch passes any
            # frontier-based settle (seen live: three streams each
            # missing the same trailing DELETED). Creating one marker
            # pod AFTER quiesce closes it: per-stream delivery is
            # revision-ordered, so a stream that has consumed the
            # sentinel's ADDED (whose rec rv DOES advance the
            # frontier) has consumed every earlier event. The sentinel
            # carries a nodeSelector no hollow node matches — it never
            # schedules, so its ADDED is the last pods event of the
            # run — and _audit_drain excludes it from the compared
            # sets. Snapshot in the SAME lock hold the barrier check
            # passes in (a second hold would reopen the window).
            sentinel = api.Pod(
                metadata=api.ObjectMeta(name=_AUDIT_SENTINEL,
                                        namespace="default"),
                spec=api.PodSpec(
                    node_selector={"watch-audit": "barrier"},
                    containers=[api.Container(
                        name="c", image="img",
                        resources=api.ResourceRequirements(
                            requests={"cpu": parse_quantity("1m"),
                                      "memory": parse_quantity("1Mi")}
                        ))]))
            barrier_rev = int(
                inproc.create("pods", sentinel)
                .metadata.resource_version)
            audit_deadline = clock.monotonic() + 5.0
            while True:
                with audit_lock:
                    settled = (truth_st["last"] >= barrier_rev
                               and all(st["last"] >= barrier_rev
                                       for st in audit_states))
                    if settled or clock.monotonic() >= audit_deadline:
                        truth = set(truth_st["seen"])
                        for st in audit_states:
                            missing = truth - st["seen"]
                            extra = st["seen"] - truth
                            result.watch_audit_missed += len(missing)
                            result.watch_audit_extra += len(extra)
                            result.watch_audit_dups += st["dups"]
                            result.watch_audit_redelivered += (
                                st["redelivered"])
                            for rec in sorted(missing):
                                result.watch_audit_diff.append(
                                    (st["name"], "missed") + rec)
                            for rec in sorted(extra):
                                result.watch_audit_diff.append(
                                    (st["name"], "extra") + rec)
                        break
                clock.sleep(0.02)
            result.watch_audit_streams = len(audit_states)

        result.services_final = services_now() or []
        result.services_ok = result.services_final == expected_services
        result.jobs_completed = max(0, completed_jobs())
        result.dead_bound = max(0, dead_bound_count())
        d_final = registry.get("deployments", plan.deployment, "default")
        result.hpa_in_band_final = hpa_in_band(wl.demand,
                                               d_final.spec.replicas)
        with lock:
            result.duplicate_bindings = len(duplicates)
            latencies = sorted(crowd_bound[n] - crowd_created[n]
                               for n in crowd_bound if n in crowd_created)
            stamps = list(bind_stamps)
        result.bind_samples = len(latencies)
        result.bind_p50_s = round(_percentile(latencies, 0.50), 4)
        result.bind_p99_s = round(_percentile(latencies, 0.99), 4)

        # ---- the applied trace vs the pure replay
        trace = wl.trace()
        result.events_applied = sum(len(v) for v in trace.values())
        result.schedule_replayed = trace == sched_pure
        result.backoff_requeues = round(
            global_metrics.counter_sum("job_backoff_requeues_total")
            - backoff_base, 1)

        # ---- per-phase bind throughput (replay thirds)
        span = max(1e-6, t_end - t_start)
        for i in range(3):
            lo = t_start + span * i / 3.0
            hi = t_start + span * (i + 1) / 3.0
            n = sum(1 for s in stamps if lo <= s < hi)
            result.phases.append({
                "phase": i,
                "binds": n,
                "binds_per_sec": round(n / (span / 3.0), 1)})

        # ---- server-side API latency over the replay window
        merged: List[float] = []
        calls = 0
        for labels, samples in metrics.summary_samples(
                LATENCY_METRIC).items():
            if dict(labels).get("resource", "").endswith(":batch"):
                continue
            merged.extend(samples)
            calls += len(samples)
        merged.sort()
        result.api_p99_ms = round(_percentile(merged, 0.99) / 1e3, 2)
        result.api_calls = calls

        if not ok:
            result.detail = (
                f"crowd {len(crowd_bound)}/{len(wl.crowd_pods)} bound, "
                f"jobs {result.jobs_completed}/{result.jobs_expected} "
                f"complete, services={result.services_final} "
                f"(want {expected_services}), "
                f"dead_bound={result.dead_bound}, "
                f"hpa actual={d_final.spec.replicas} "
                f"ideal={ideal_replicas(wl.demand)} "
                f"status(replicas={d_final.status.replicas} "
                f"avail={d_final.status.available_replicas} "
                f"unavail={d_final.status.unavailable_replicas} "
                f"updated={d_final.status.updated_replicas})")
        return result
    finally:
        stop_threads.set()
        node_chaos.stop()
        hpa_ctl.stop()
        node_ctl.stop()
        ds_ctl.stop()
        job_ctl.stop()
        deploy_ctl.stop()
        rc_mgr.stop()
        sched.stop()
        factory.stop()
        fleet.stop()
        for st in ([truth_st] if truth_st else []) + audit_states:
            if st.get("watcher") is not None:
                st["watcher"].stop()
        if pool is not None:
            pool.stop()
        else:
            server.stop()


# ------------------------------------------------------------ flash drain

@dataclass
class FlashDrainResult:
    """`run_flash_drain_soak` verdict — the priority-preemption
    acceptance scenario: a fleet saturated with low-priority batch
    fill, one high-priority surge, 5% API faults and a node kill, all
    gated on the surge-bind burn-rate timeline and a post-hoc oracle
    audit of every eviction."""

    converged: bool
    n_nodes: int
    seed: int
    ticks: int
    #: the tick the surge landed at (pure per seed)
    surge_tick: int = -1
    #: applied drain trace == plan.schedule()["drain"]
    schedule_replayed: bool = False
    node_schedule_replayed: bool = True
    events_applied: int = 0
    events_expected: int = 0
    killed: List[str] = field(default_factory=list)
    # ---- fill (low-priority batch) population
    fill_pods: int = 0
    fill_bound: int = 0
    # ---- surge bind SLO (injection -> spec.nodeName observed)
    surge_pods: int = 0
    surge_bound: int = 0
    surge_bound_fast: int = 0
    surge_bind_p50_s: float = 0.0
    surge_bind_p99_s: float = 0.0
    surge_bind_limit_s: float = 5.0
    # ---- preemption ledger (counter deltas over this run)
    preemption_rounds: int = 0
    victims_evicted: int = 0
    #: post-hoc oracle audit violations (MUST be 0): evicted a
    #: >=-priority victim, evicted when a feasible non-preempting node
    #: existed, or diverged from the oracle's minimal victim set
    wrongful_evictions: int = 0
    wrongful_detail: List[str] = field(default_factory=list)
    duplicate_bindings: int = 0
    dead_bound: int = 0
    # ---- burn-rate alert timeline (replayable TRIP/CLEAR)
    scrape_samples: int = 0
    alerts: List[Dict] = field(default_factory=list)
    alert_clear_limit_ticks: int = 8
    flight_bundles: List[str] = field(default_factory=list)
    detail: str = ""

    @property
    def surge_bind_ok(self) -> Optional[bool]:
        if self.surge_pods == 0:
            return None  # the plan drew no surge: nothing to gate
        return (self.surge_bound >= self.surge_pods
                and self.surge_bind_p99_s < self.surge_bind_limit_s)

    @property
    def alerts_ok(self) -> Optional[bool]:
        """Surge TRIP/CLEAR gate, same semantics as the workload
        soak's crowd gate: the surge cannot bind in the tick it lands
        (victims must drain first), so it MUST trip the surge
        fast-burn alert, and every TRIP must CLEAR within
        alert_clear_limit_ticks samples once preemption frees capacity
        and the surge binds."""
        if self.scrape_samples == 0 or self.surge_pods == 0:
            return None
        surge = [a for a in self.alerts
                 if a["slo"] == SURGE_BIND_SLO.name]
        trips = [a for a in surge if a["action"] == "TRIP"]
        if not trips:
            return False
        for i, a in enumerate(surge):
            if a["action"] != "TRIP":
                continue
            clear = next((b for b in surge[i + 1:]
                          if b["action"] == "CLEAR"), None)
            if clear is None or (clear["sample"] - a["sample"]
                                 > self.alert_clear_limit_ticks):
                return False
        return True

    @property
    def slo_ok(self) -> bool:
        return bool(self.converged and self.schedule_replayed
                    and self.node_schedule_replayed
                    and self.surge_bind_ok is not False
                    and self.alerts_ok is not False
                    and self.wrongful_evictions == 0
                    and self.duplicate_bindings == 0
                    and self.dead_bound == 0)

    def state_summary(self) -> Dict:
        """The canonical deterministic projection — what two same-seed
        invocations are compared on. Wall-clock latencies and the
        exact victim pods are OUT (eviction order races fill arrival
        within a tick); the surge population, kill set, audit verdict
        and the surge alert timeline are IN."""
        return {
            "surge_tick": self.surge_tick,
            "surge_pods": self.surge_pods,
            "surge_bound": self.surge_bound,
            "fill_pods": self.fill_pods,
            "killed": list(self.killed),
            "wrongful_evictions": self.wrongful_evictions,
            "duplicate_bindings": self.duplicate_bindings,
            "converged": self.converged,
            "surge_alerts": [[a["sample"], a["action"]]
                             for a in self.alerts
                             if a["slo"] == SURGE_BIND_SLO.name],
        }

    def as_dict(self) -> Dict:
        d = asdict(self)
        d["surge_bind_ok"] = self.surge_bind_ok
        d["alerts_ok"] = self.alerts_ok
        d["slo_ok"] = self.slo_ok
        return d


def run_flash_drain_soak(n_nodes: int = 10, seed: int = 0,
                         plan: Optional[WorkloadPlan] = None,
                         tick_wall_s: float = 0.4,
                         fault_rate: float = 0.05,
                         node_kill_fraction: float = 0.10,
                         kill_tick: Optional[int] = None,
                         surge_bind_limit_s: float = 5.0,
                         timeout: float = 180.0,
                         heartbeat_interval: float = 0.5,
                         monitor_period: float = 0.1,
                         monitor_grace_period: float = 1.5,
                         pod_eviction_timeout: float = 0.3,
                         alert_clear_limit_ticks: int = 8,
                         flight_dir: Optional[str] = None,
                         clock: Optional[Clock] = None
                         ) -> FlashDrainResult:
    """Flash-crowd drain replay: ONLY the drain generator's stream,
    with fleet-saturating requests (900m fills on 4-CPU hollow nodes:
    4 slots per node; the default plan's fill volume saturates the
    post-kill fleet well before the surge can land), under the same
    5% API-fault injection as the workload soak plus a 10% node kill
    at `kill_tick` (defaults to the quarter point — BEFORE the surge,
    which lands in the second half, so the surge hits a fleet that
    already lost capacity).

    The surge pods are strictly higher priority than the fill; binding
    them requires the scheduler's preemption pass (sched/preemption.py)
    to evict minimal fill victim sets, and the priority-ordered pending
    queue to hand the freed capacity to the surge rather than the fill
    backlog. Gates: the surge-bind burn-rate alert must TRIP at the
    surge tick and CLEAR within `alert_clear_limit_ticks` samples,
    every surge pod must bind with p99 under `surge_bind_limit_s`,
    zero duplicate bindings, zero pods on dead nodes, and the post-hoc
    serial-oracle audit of every recorded preemption round must come
    back empty (zero wrongful evictions — each violation increments
    the pinned wrongful counter and flight-dumps when a recorder is
    armed)."""
    clock = clock or REAL
    if plan is None:
        plan = WorkloadPlan(
            seed=seed,
            drain_fill_rate=0.9, drain_fill_min=5, drain_fill_max=8,
            drain_fill_cpu_milli=900, drain_fill_mem_mi=64,
            drain_surge_cpu_milli=900, drain_surge_mem_mi=64)
    seed = plan.seed
    drain_pure = plan.schedule()["drain"]
    fault_plan = FaultPlan(seed=seed, error_rate=fault_rate)
    node_plan = NodeFaultPlan(seed=seed,
                              kill_fraction=node_kill_fraction)
    kill_tick = (plan.ticks // 4 if kill_tick is None else kill_tick)

    metrics = MetricsRegistry()
    registry = Registry()
    server = ApiServer(registry, port=0, metrics=metrics).start()
    chaos = ChaosClient(HttpClient(server.url), fault_plan)

    result = FlashDrainResult(
        converged=False, n_nodes=n_nodes, seed=seed, ticks=plan.ticks,
        surge_tick=plan.surge_tick(),
        surge_bind_limit_s=surge_bind_limit_s,
        alert_clear_limit_ticks=alert_clear_limit_ticks,
        events_expected=len(drain_pure))

    recorder = (FlightRecorder(flight_dir, clock=clock)
                if flight_dir else None)
    tick_now = [0]
    sampled_tick = [-1]

    def _on_trip(ev):
        if recorder is not None:
            recorder.dump(f"slo-{ev.slo}", scraper=scraper,
                          tracer=_obs_tracer(),
                          chaos={"tick": tick_now[0]},
                          extra=ev.to_dict())

    scraper = FleetScraper(
        [HttpTarget("apiserver", server.url + "/metrics"),
         RegistryTarget("fleet", global_metrics)],
        clock=clock, cadence_s=tick_wall_s, seed=seed)
    evaluator = BurnRateEvaluator(list(FLEET_SLOS), on_trip=_on_trip)
    rounds_base = global_metrics.counter_sum(PREEMPTION_COUNTERS[0])
    victims_base = global_metrics.counter_sum(PREEMPTION_COUNTERS[1])

    fleet = HollowFleet(chaos, n_nodes,
                        heartbeat_interval=heartbeat_interval,
                        jitter_seed=seed).run()
    factory = ConfigFactory(chaos, rate_limit=False).start()
    pre = PreemptionPass(seed=seed)
    sched = BatchScheduler(factory.create_batch(preemption=pre)).run()
    node_ctl = NodeController(
        chaos, monitor_period=monitor_period,
        monitor_grace_period=monitor_grace_period,
        pod_eviction_timeout=pod_eviction_timeout,
        eviction_qps=1000.0, eviction_burst=1000).run()

    wl = WorkloadChaos(chaos, plan, clock=clock)
    node_chaos = NodeChaos(fleet, node_plan)

    lock = threading.Lock()
    bound_to: Dict[str, str] = {}
    duplicates: List[Tuple[str, str, str]] = []
    surge_created: Dict[str, float] = {}
    surge_tick_of: Dict[str, int] = {}
    surge_bound: Dict[str, float] = {}       # name -> bind latency s
    fill_bound: Dict[str, float] = {}
    stop_threads = threading.Event()

    def _on_surge(names):
        # synchronous with apply_tick (the _on_crowd pattern): the
        # created counter and THIS tick's scrape sample both move
        # before the scheduler can have bound anything, so the TRIP
        # edge replays deterministically at the surge tick
        surge_created.update({n: time.monotonic() for n in names})
        surge_tick_of.update({n: tick_now[0] for n in names})
        metrics.inc(SURGE_COUNTERS[0], by=float(len(names)))
        if sampled_tick[0] != tick_now[0]:
            sampled_tick[0] = tick_now[0]
            evaluator.observe(scraper.sample(t=float(tick_now[0])))

    wl.on_surge = _on_surge

    def tracker():
        # registry sweep: duplicate-binding ledger + surge bind stamps
        # (server-side truth — spec.nodeName in the store); a surge
        # bind inside the fast limit moves the good counter the
        # burn-rate CLEAR rides on
        while not stop_threads.is_set():
            try:
                pods, _ = registry.list("pods", "default")
            except Exception:
                time.sleep(0.03)
                continue
            now = time.monotonic()
            with lock:
                for p in pods:
                    node = p.spec.node_name
                    if not node:
                        continue
                    prev = bound_to.get(p.metadata.uid)
                    if prev is not None and prev != node:
                        duplicates.append((p.metadata.uid, prev, node))
                    bound_to[p.metadata.uid] = node
                    name = p.metadata.name
                    if (name.startswith("surge-")
                            and name in surge_created
                            and name not in surge_bound):
                        lat = now - surge_created[name]
                        surge_bound[name] = lat
                        metrics.observe(SURGE_BIND_HISTOGRAM, lat)
                        if lat <= surge_bind_limit_s:
                            metrics.inc(SURGE_COUNTERS[1])
                    elif (name.startswith("fill-")
                          and name not in fill_bound):
                        fill_bound[name] = now
            time.sleep(0.03)

    threading.Thread(target=tracker, daemon=True,
                     name="flash-drain-tracker").start()

    def wait_until(cond, deadline):
        while clock.monotonic() < deadline:
            if cond():
                return True
            clock.sleep(0.05)
        return cond()

    try:
        deadline = clock.monotonic() + timeout
        if not wait_until(
                lambda: len(factory.node_lister.list()) >= n_nodes,
                deadline):
            result.detail = "fleet never registered"
            return result
        # warm the engine's compile caches (including the preemption
        # kernel via the scheduler's first victim search shapes) while
        # idle — an XLA compile inside the replay would bill compiler
        # seconds to the surge's bind latency
        from .benchmark import _warmup_batch
        _warmup_batch(sched, factory)

        dead: set = set()
        for tick in range(plan.ticks):
            tick_now[0] = tick
            # surges that landed on EARLIER ticks must be bound before
            # this tick's sample or the CLEAR edge races the scrape
            # (the workload soak's crowd-quiesce rule); a preempted
            # bind pays victim grace plus a requeue round, so the cap
            # dominates a couple of eviction rounds
            due = [n for n, t0 in surge_tick_of.items() if t0 < tick]
            if due:
                def _surges_quiesced():
                    with lock:
                        return all(n in surge_bound for n in due)
                wait_until(_surges_quiesced,
                           clock.monotonic() + max(8.0,
                                                   4.0 * tick_wall_s))
            wl.apply_tick(tick, deadline, generators=("drain",))
            if node_kill_fraction > 0 and tick == kill_tick:
                result.killed = node_chaos.kill()
                dead = set(result.killed)
                result.node_schedule_replayed = (
                    result.killed
                    == node_plan.schedule(fleet.node_names())["kill"])
                if recorder is not None:
                    recorder.dump("chaos-node-kill", scraper=scraper,
                                  tracer=_obs_tracer(),
                                  chaos={"tick": tick,
                                         "victims": result.killed})
            if sampled_tick[0] != tick:
                evaluator.observe(scraper.sample(t=float(tick)))
            time.sleep(tick_wall_s)

        # ---- quiesce: every surge pod bound, nothing on dead nodes
        # (the fill backlog is EXPECTED to stay pending — the fleet is
        # sized so the drain saturates it; fills are reported, not
        # gated)
        def surge_settled():
            with lock:
                return len(surge_bound) >= len(wl.surge_pods)

        def dead_bound_count():
            try:
                pods, _ = registry.list("pods", "default")
            except Exception:
                return -1
            return sum(1 for p in pods if p.spec.node_name in dead)

        ok = wait_until(lambda: surge_settled()
                        and dead_bound_count() == 0, deadline)
        result.converged = ok

        # drain samples past the replay: the surge can land on the
        # final tick; its CLEAR edge needs samples after binds settle
        for extra in range(6):
            evaluator.observe(
                scraper.sample(t=float(plan.ticks + extra)))
        result.scrape_samples = len(scraper.series())
        result.alerts = evaluator.events_dict()

        # ---- the wrongful-eviction gate: every recorded round
        # replayed through the serial oracle post hoc; any divergence
        # is counted on the pinned counter and flight-dumped
        result.wrongful_detail = pre.audit()
        result.wrongful_evictions = len(result.wrongful_detail)
        for _ in result.wrongful_detail:
            global_metrics.inc(PREEMPTION_COUNTERS[2])
        result.preemption_rounds = int(
            global_metrics.counter_sum(PREEMPTION_COUNTERS[0])
            - rounds_base)
        result.victims_evicted = int(
            global_metrics.counter_sum(PREEMPTION_COUNTERS[1])
            - victims_base)

        with lock:
            result.duplicate_bindings = len(duplicates)
            lats = sorted(surge_bound.values())
            result.surge_bound = len(surge_bound)
            result.surge_bound_fast = sum(
                1 for v in lats if v <= surge_bind_limit_s)
            result.fill_bound = len(fill_bound)
        result.surge_pods = len(wl.surge_pods)
        result.fill_pods = len(wl.drain_pods)
        result.surge_bind_p50_s = round(_percentile(lats, 0.50), 4)
        result.surge_bind_p99_s = round(_percentile(lats, 0.99), 4)
        result.dead_bound = max(0, dead_bound_count())

        trace = wl.trace()
        result.events_applied = len(trace["drain"])
        result.schedule_replayed = trace["drain"] == drain_pure

        if recorder is not None:
            if result.wrongful_evictions or result.duplicate_bindings:
                recorder.dump(
                    "preemption-violation", scraper=scraper,
                    tracer=_obs_tracer(),
                    chaos={"wrongful": result.wrongful_detail,
                           "duplicates": [list(d) for d in duplicates]})
            result.flight_bundles = list(recorder.bundles)

        if not ok:
            result.detail = (
                f"surge {result.surge_bound}/{result.surge_pods} "
                f"bound, fills {result.fill_bound}/{result.fill_pods},"
                f" dead_bound={result.dead_bound}, "
                f"rounds={result.preemption_rounds} "
                f"victims={result.victims_evicted}")
        return result
    finally:
        stop_threads.set()
        node_chaos.stop()
        node_ctl.stop()
        sched.stop()
        factory.stop()
        fleet.stop()
        server.stop()
