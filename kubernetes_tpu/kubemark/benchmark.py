"""BenchmarkScheduling, ported.

Reference: test/integration/scheduler_test.go:278-354 — in-process master
+ scheduler, 1000 fake nodes (4 CPU / 32Gi / 32-pod cap :329-354), N pods
created by 30 concurrent writer goroutines (:379), clock stops when the
scheduled-pod lister has seen every pod. Here the master is the in-proc
registry, the nodes come from a HollowFleet (full kubemark wiring: the
fleet also confirms pods Running), and the scheduler is either the serial
control loop or the TPU batch loop — the benchmark measures the whole
bind pipeline, not just the scoring math (bench.py measures that).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..api.client import InProcClient
from ..api.registry import Registry
from ..core import types as api
from ..core.quantity import parse_quantity
from ..sched.batch import BatchScheduler
from ..sched.factory import ConfigFactory
from ..sched.scheduler import Scheduler
from .fleet import HollowFleet

WRITER_THREADS = 30  # ref: scheduler_test.go:379


@dataclass
class BenchmarkResult:
    n_nodes: int
    n_pods: int
    scheduled: int
    running: int
    elapsed_s: float          # create-start -> all pods bound
    pods_per_sec: float
    mode: str                 # "batch" | "serial"
    started_at: float = 0.0   # epoch of create-start (profilers scope
    #                           samples to [started_at, +elapsed_s])
    # batch mode: the engine's host->device transfer accounting over the
    # MEASURED window (warmup excluded) — full vs delta upload tiles and
    # bytes; None in serial mode
    upload_stats: Optional[dict] = None


_BENCH_REQUESTS = {"cpu": parse_quantity("100m"),
                   "memory": parse_quantity("64Mi")}


def _bench_pod(i: int) -> api.Pod:
    # shape from the reference fixture: 100m / no memory request
    # isn't specified there; keep requests small enough that 1000x32-cap
    # nodes absorb any N used in tests/benches
    return api.Pod(
        metadata=api.ObjectMeta(name=f"bench-pod-{i:06d}",
                                namespace="default",
                                labels={"app": "bench"}),
        spec=api.PodSpec(containers=[api.Container(
            name="c", image="benchmark-image",
            resources=api.ResourceRequirements(
                requests=dict(_BENCH_REQUESTS)))]),
        status=api.PodStatus(phase="Pending"))


def _warmup_batch(sched: BatchScheduler, factory: ConfigFactory) -> None:
    """Compile the engine's scan programs at the benchmark's real shapes
    (the scheduler's own encoder path + every chunk rung) outside the
    measured window."""
    c = sched.config
    inc = sched._incremental()
    if inc is not None:
        # the measured path: incremental arrays (node axis = n_cap)
        enc = inc.encode_tile([_bench_pod(0)],
                              factory.service_lister.list(),
                              factory.controller_lister.list())
        for chunk in (c.min_pad, c.bulk_chunk, c.tile_size):
            c.engine.run_chunked(enc, chunk)
        return
    from ..sched.device import ClusterSnapshot
    snap = ClusterSnapshot(
        nodes=factory.node_lister.list(),
        existing_pods=[],
        services=factory.service_lister.list(),
        controllers=factory.controller_lister.list(),
        pending_pods=[_bench_pod(0)])
    for chunk in (c.min_pad, c.bulk_chunk, c.tile_size):
        c.engine.schedule(snap, chunk=chunk)


def run_scheduling_benchmark(n_nodes: int = 1000, n_pods: int = 1000,
                             mode: str = "batch",
                             max_pods_per_node: int = 32,
                             wait_running: bool = False,
                             timeout_s: float = 300.0,
                             registry: Optional[Registry] = None,
                             store_publish_inline: bool = False,
                             chaos_seed: Optional[int] = None,
                             chaos_error_rate: float = 0.01,
                             txn_commit: bool = True,
                             delta_uploads: bool = True
                             ) -> BenchmarkResult:
    """Stand up master + fleet + scheduler, blast pods from 30 writers,
    measure time until every pod is bound (and optionally Running).

    store_publish_inline: build the registry over a store that fans
    watch events out while still holding its ledger lock — the
    pre-split commit serialization, kept as the control arm of
    bench.py's --store-ab.

    txn_commit: False restores the pre-txn commit shape — registry
    batch verbs route store.batch() per 1024-op chunk and the fleet's
    status pump caps its drain at 1024 — the control arm of bench.py's
    --txn-ab. True (default) lands each tile/burst in one multi-key
    transaction (one revision window, one WAL frame).

    chaos_seed: wrap every component's client in the seeded chaos
    injector (chaos.ChaosClient at chaos_error_rate on all verbs) so
    the perf number is recorded UNDER fault load — the bench.py
    --chaos-seed arm. None (the default) leaves the hot path
    untouched.

    delta_uploads: False forces the engine to re-upload the full node
    tables every tile (the pre-mirror behavior) — the control arm of
    the delta-scatter A/B in tools/profile_e2e.py."""
    # GIL slice: r2 measured 1ms best (the scheduler thread parked
    # behind 30 writers at every dispatch); after r4's contention fixes
    # (thread-local uids, in-place rv stamping, informer-riding
    # counter) the default 5ms wins — fewer forced handoffs across ~40
    # threads — and tightens the run-to-run spread (A/B in PROFILE_e2e.md)
    import sys
    sys.setswitchinterval(0.005)
    if registry is None and (store_publish_inline or not txn_commit):
        from ..core.store import Store
        registry = Registry(
            store=Store(publish_inline=store_publish_inline),
            txn_commit=txn_commit)
    registry = registry or Registry()
    client = InProcClient(registry)
    if chaos_seed is not None:
        from ..chaos import ChaosClient, FaultPlan
        client = ChaosClient(client, FaultPlan(seed=chaos_seed,
                                               error_rate=chaos_error_rate))
    # heartbeats quiesce during the measured window: the reference's
    # BenchmarkScheduling fixture has NO kubelets (nodes are API
    # objects, scheduler_test.go:329) — the fleet is here to confirm
    # Running, and its r4 shard-staggered beats would otherwise drip
    # ~500 status writes into every 6s of a ~5s window
    fleet = HollowFleet(client, n_nodes, cpu="4", memory="32Gi",
                        max_pods=max_pods_per_node,
                        heartbeat_interval=600.0,
                        status_chunk=0 if txn_commit else 1024).run()
    factory = ConfigFactory(client, rate_limit=False).start()
    if mode == "batch":
        sched = BatchScheduler(factory.create_batch(
            commit_chunk=0 if txn_commit else 1024)).run()
        sched.config.engine.delta_uploads = delta_uploads
    elif mode == "serial":
        sched = Scheduler(factory.create()).run()
    else:
        raise ValueError(f"unknown mode {mode!r}")

    try:
        # wait until the scheduler's node cache sees the fleet
        deadline = time.time() + timeout_s
        while time.time() < deadline and \
                len(factory.node_lister.list()) < n_nodes:
            time.sleep(0.05)

        if mode == "batch":
            # warm the XLA compile cache at the real node-table shape
            # before the clock starts: a live scheduler process has warm
            # caches (the reference benchmark likewise measures a warm
            # in-process scheduler, scheduler_test.go:278), and compile
            # happens once per shape, not per tile
            _warmup_batch(sched, factory)
            # transfer accounting restarts at the measured window (the
            # warmup's uploads are compile-cache priming, not steady
            # state; the device mirror itself stays warm, as in a live
            # scheduler)
            sched.config.engine.upload_stats = {
                k: 0 for k in sched.config.engine.upload_stats}

        # the live-server GC posture (utils/gctune.py): the booted
        # fleet + node caches freeze out of the young generations and
        # gen-0 stops firing every ~700 allocations (it showed at ~25%
        # of profile ticks via jax's per-collection callback). Applies
        # to both modes — hyperkube server entries make the same move.
        from ..utils.gctune import tuned_gc
        gc_ctx = tuned_gc()
        gc_ctx.__enter__()

        # completion counter rides the scheduler's OWN scheduled-pod
        # informer (exactly the reference: BenchmarkScheduling waits on
        # the config's ScheduledPodLister, scheduler_test.go:278) — a
        # separate watch would add a 4th pods watcher to every store
        # fan-out inside the measured window
        bound = set()
        bound_lock = threading.Lock()
        all_bound = threading.Event()

        def count_binding(pod):
            if pod.metadata.name.startswith("bench-pod-") and \
                    pod.spec.node_name:
                with bound_lock:
                    bound.add(pod.metadata.name)
                    if len(bound) >= n_pods:
                        all_bound.set()

        factory.scheduled_observers.append(count_binding)

        start = time.time()
        next_i = iter(range(n_pods))
        lock = threading.Lock()
        # each writer claims a chunk and POSTs it through the batched
        # create path: one store window + one watch flush per chunk
        # instead of per pod (the create storm was ~1.6s of the 30k-pod
        # wall time when every pod paid its own lock + fan-out)
        chunk = 256

        # columnar create: the 30 writers ship one template + a name
        # column per chunk instead of a materialized dataclass per pod
        # (registry.create_from_template — validation once, shared
        # spec/status, fresh metadata per row). The reference's
        # BenchmarkScheduling likewise stamps pods off one template
        # fixture (test/integration/scheduler_test.go:329).
        template = _bench_pod(0)

        def writer():
            while True:
                with lock:
                    ids = []
                    for _ in range(chunk):
                        i = next(next_i, None)
                        if i is None:
                            break
                        ids.append(i)
                if not ids:
                    return
                names = [f"bench-pod-{i:06d}" for i in ids]
                while True:
                    try:
                        client.create_from_template(
                            "pods", template, names, "default")
                        break
                    except Exception:
                        # only injected faults are retried (a fault
                        # fires before the call reaches the registry,
                        # so the claimed chunk is never half-created);
                        # real errors keep crashing the writer
                        if chaos_seed is None or time.time() > deadline:
                            raise
                        time.sleep(0.01)

        writers = [threading.Thread(target=writer, daemon=True)
                   for _ in range(WRITER_THREADS)]
        for w in writers:
            w.start()
        for w in writers:
            w.join()

        all_bound.wait(timeout=max(0.0, deadline - time.time()))
        elapsed = time.time() - start
        factory.scheduled_observers.remove(count_binding)
        with bound_lock:
            scheduled = len(bound)

        running = 0
        if wait_running:
            while time.time() < deadline:
                pods, _ = registry.list("pods", "default")
                running = sum(1 for p in pods
                              if p.metadata.name.startswith("bench-pod-")
                              and p.status.phase == "Running")
                if running >= n_pods:
                    break
                time.sleep(0.05)

        return BenchmarkResult(
            n_nodes=n_nodes, n_pods=n_pods, scheduled=scheduled,
            running=running, elapsed_s=elapsed,
            pods_per_sec=scheduled / elapsed if elapsed > 0 else 0.0,
            mode=mode, started_at=start,
            upload_stats=(dict(sched.config.engine.upload_stats)
                          if mode == "batch" else None))
    finally:
        try:
            gc_ctx.__exit__(None, None, None)
        except NameError:
            pass  # failure before the tuning point
        sched.stop()
        factory.stop()
        fleet.stop()


def main() -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--pods", type=int, default=1000)
    ap.add_argument("--mode", choices=["batch", "serial"], default="batch")
    ap.add_argument("--wait-running", action="store_true")
    ap.add_argument("--store-publish-inline", action="store_true",
                    help="control arm: fan watch events out under the "
                         "store's ledger lock (pre-split behavior)")
    ap.add_argument("--no-txn", action="store_true",
                    help="control arm: per-1024-op store.batch() chunks "
                         "instead of one multi-key txn per tile/burst")
    args = ap.parse_args()
    r = run_scheduling_benchmark(
        args.nodes, args.pods, args.mode,
        wait_running=args.wait_running,
        store_publish_inline=args.store_publish_inline,
        txn_commit=not args.no_txn)
    print(json.dumps({
        "metric": f"e2e_scheduling_throughput_{r.mode}",
        "nodes": r.n_nodes, "pods": r.n_pods, "scheduled": r.scheduled,
        "elapsed_s": round(r.elapsed_s, 3),
        "value": round(r.pods_per_sec, 1), "unit": "pods/sec",
        "vs_baseline": round(r.pods_per_sec / 50.0, 1)}))


if __name__ == "__main__":
    main()
