"""Node lifecycle controller: heartbeat monitoring + rate-limited pod
eviction.

Reference: pkg/controller/node/nodecontroller.go — monitorNodeStatus
(:380-460): per monitor tick, mark nodes whose heartbeat is older than the
grace period Ready=Unknown; once a node has been not-ready/unknown longer
than podEvictionTimeout, queue it on a rate-limited eviction queue
(RateLimitedTimedQueue, pkg/controller/node/rate_limited_queue.go); a node
going Ready again cancels its eviction; eviction deletes every pod bound
to the node and records events. Nodes that vanish from the API get their
pods evicted too (:378-382).

Defaults mirror the reference flags (controllermanager.go):
--node-monitor-period=5s, --node-monitor-grace-period=40s,
--pod-eviction-timeout=5m, --deleting-pods-qps=0.1 burst 10.

Forward-ported beyond the v1.1 reference (DIVERGENCES.md):

- Partition safety valve: when more than `unhealthy_threshold` of the
  fleet is NotReady/Unknown simultaneously, the likeliest explanation
  is a MASTER-side partition (the controller can't reach anything, or
  the apiserver lost the kubelets), not half the datacenter dying at
  once — so evictions HALT (queue freezes, drain stops) and resume
  only when the unhealthy fraction drops back under the threshold.
  This is the later reference's --unhealthy-zone-threshold=0.55
  collapsed to one zone.
- Flap damping: a node bouncing Ready<->NotReady inside the damping
  window (a sick kubelet, a lossy link) is never queued for eviction
  while flapping — without it, each bounce queues/cancels the node and
  a drain racing a flap evicts pods off a node that is Ready again.
- Evictions delete pods with a uid precondition, so a racing
  same-name replacement pod is never killed by a stale drain.
"""

from __future__ import annotations

import threading
from dataclasses import replace
from typing import Dict, List, Optional, Set

from ..core import types as api
from ..core.errors import Conflict, NotFound
from ..utils.clock import Clock, RealClock
from ..utils.ratelimit import TokenBucketRateLimiter


class _NodeHealth:
    __slots__ = ("probe_timestamp", "ready_transition_timestamp", "status",
                 "last_heartbeat", "transitions")

    def __init__(self, probe: float, transition: float, status: str,
                 heartbeat: Optional[str] = None):
        self.probe_timestamp = probe
        self.ready_transition_timestamp = transition
        self.status = status
        self.last_heartbeat = heartbeat
        # recent Ready-status transition times (flap detection); pruned
        # to the damping window on every observation
        self.transitions: List[float] = []


class NodeController:
    def __init__(self, client, monitor_period: float = 5.0,
                 monitor_grace_period: float = 40.0,
                 pod_eviction_timeout: float = 300.0,
                 eviction_qps: float = 0.1, eviction_burst: int = 10,
                 clock: Optional[Clock] = None, recorder=None,
                 allocate_node_cidrs: bool = False,
                 cluster_cidr: str = "",
                 unhealthy_threshold: float = 0.55,
                 partition_min_cluster: int = 3,
                 flap_threshold: int = 3,
                 flap_window: Optional[float] = None):
        """allocate_node_cidrs + cluster_cidr: assign each node a /24
        pod CIDR from the cluster range (nodecontroller.go:62,137
        --allocate-node-cidrs; the route controller consumes
        node.spec.pod_cidr).

        unhealthy_threshold: when MORE than this fraction of the fleet
        is NotReady/Unknown at once, suspect a master-side partition
        and halt all evictions until the fraction recovers. Only
        applies once the fleet has at least partition_min_cluster
        observed nodes (a 1-node cluster losing its node is not a
        partition signal).

        flap_threshold / flap_window: a node with >= flap_threshold
        Ready-status transitions inside flap_window seconds is
        'flapping' and is not queued for eviction until it settles
        (window defaults to the monitor grace period)."""
        if allocate_node_cidrs:
            if not cluster_cidr:
                raise ValueError(
                    "allocate_node_cidrs requires cluster_cidr "
                    "(nodecontroller.go:137-139)")
            import ipaddress
            # fail at construction, not in the monitor thread — a lazy
            # ValueError would kill health monitoring cluster-wide
            ipaddress.ip_network(cluster_cidr)
        self.allocate_node_cidrs = allocate_node_cidrs
        self.cluster_cidr = cluster_cidr
        self.client = client
        self.monitor_period = monitor_period
        self.monitor_grace_period = monitor_grace_period
        self.pod_eviction_timeout = pod_eviction_timeout
        self.clock = clock or RealClock()
        self.recorder = recorder
        self.eviction_limiter = TokenBucketRateLimiter(
            eviction_qps, eviction_burst, self.clock)
        self.unhealthy_threshold = unhealthy_threshold
        self.partition_min_cluster = partition_min_cluster
        self.flap_threshold = flap_threshold
        self.flap_window = (flap_window if flap_window is not None
                            else monitor_grace_period)
        # node name -> health bookkeeping (nodeStatusMap :95)
        self._health: Dict[str, _NodeHealth] = {}
        self._known_nodes: Set[str] = set()
        self._eviction_queue: Set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # observability: the chaos soak / bench read these
        self.evictions_halted = False      # partition valve engaged
        self.evictions_total = 0           # pods deleted by eviction
        self.eviction_drains_total = 0     # nodes fully drained
        self.partition_halts_total = 0     # valve engage count
        self.flap_damped_total = 0         # evictions deferred by damping

    # -- status monitoring ------------------------------------------------

    @staticmethod
    def _ready_condition(node: api.Node) -> Optional[api.NodeCondition]:
        for c in node.status.conditions:
            if c.type == "Ready":
                return c
        return None

    def _observe(self, node: api.Node) -> str:
        """Update health bookkeeping; mark stale heartbeats Unknown.
        Returns the effective ready status (tryUpdateNodeStatus)."""
        name = node.metadata.name
        now = self.clock.now()
        ready = self._ready_condition(node)
        status = ready.status if ready else "Unknown"
        heartbeat = (ready.last_heartbeat_time if ready else "")
        prior = self._health.get(name)
        if prior is None:
            self._health[name] = _NodeHealth(now, now, status, heartbeat)
            return status
        if status != prior.status:
            prior.ready_transition_timestamp = now
            prior.status = status
            prior.transitions.append(now)
        if heartbeat != prior.last_heartbeat:
            prior.probe_timestamp = now
            prior.last_heartbeat = heartbeat

        if (status != "Unknown"
                and now - prior.probe_timestamp > self.monitor_grace_period):
            # heartbeat went stale: the node agent is gone. Any last
            # reported status goes Unknown — a kubelet that reported
            # Ready=False and then died must not leave its stale
            # diagnosis standing (monitorNodeStatus marks every
            # stale-heartbeat node Unknown, nodecontroller.go)
            status = "Unknown"
            prior.ready_transition_timestamp = now
            prior.status = status
            prior.transitions.append(now)
            self._mark_unknown(node)
            if self.recorder:
                self.recorder.eventf(node, "Normal", "NodeNotReady",
                                     "Node %s status is now: NodeNotReady",
                                     name)
        return status

    def _mark_unknown(self, node: api.Node) -> None:
        conds = [replace(c, status="Unknown",
                         reason="NodeStatusUnknown",
                         message="Kubelet stopped posting node status.")
                 if c.type in ("Ready", "OutOfDisk") else c
                 for c in node.status.conditions]
        try:
            fresh = self.client.get("nodes", node.metadata.name)
            self.client.update_status(
                "nodes", replace(fresh, status=replace(fresh.status,
                                                       conditions=conds)))
        except Exception:
            pass  # retried next tick (nodeStatusUpdateRetry)

    def _is_flapping(self, health: _NodeHealth, now: float) -> bool:
        """>= flap_threshold Ready-status transitions inside the damping
        window: the node is bouncing, not dead — deferring its eviction
        beats the queue/cancel churn (and the drain-races-a-recovery
        eviction) each bounce would cause."""
        cutoff = now - self.flap_window
        health.transitions = [t for t in health.transitions if t >= cutoff]
        return len(health.transitions) >= self.flap_threshold

    # -- eviction ---------------------------------------------------------

    def _queue_eviction(self, name: str) -> None:
        with self._lock:
            self._eviction_queue.add(name)

    def _cancel_eviction(self, name: str) -> None:
        with self._lock:
            self._eviction_queue.discard(name)

    def _drain_eviction_queue(self) -> None:
        """Rate-limited: one node's pods per token. A still-dead node is
        re-queued by the next monitor tick, so pods bound to it later are
        evicted too — the reference's RateLimitedTimedQueue keeps
        processing a node until it goes Ready."""
        failed: set = set()   # per-drain: skip, retry next drain
        while True:
            with self._lock:
                pending = self._eviction_queue - failed
                if not pending:
                    return
                name = min(pending)  # deterministic order
            if not self.eviction_limiter.try_accept():
                return
            if self.evictions_halted:
                # the partition valve can engage between drains (the
                # monitor tick runs on the same thread, but tests and
                # embedders may drive drains directly)
                return
            if not self._evict_pods(name):
                # keep the entry (a node DELETED from the API is only
                # ever queued once, so a transient failure must not
                # discard its eviction forever — the reference's
                # RateLimitedTimedQueue keeps entries until their work
                # succeeds) but move PAST it this drain: one
                # persistently failing node must not head-of-line
                # block every other node's eviction
                failed.add(name)
                continue
            self.eviction_drains_total += 1
            with self._lock:
                self._eviction_queue.discard(name)

    def _evict_pods(self, node_name: str) -> bool:
        """True when the node's pods were listed and every delete was
        accepted (NotFound counts as done); False requeues the node."""
        try:
            pods, _ = self.client.list(
                "pods", field_selector=f"spec.nodeName={node_name}")
        except Exception:
            return False
        ok = True
        for pod in pods:
            try:
                # grace 0: the node's kubelet is gone, so nobody would
                # ever confirm a graceful mark — a graced pod would sit
                # Terminating forever (the reference's eviction relies
                # on the kubelet; with the node dead, force is the only
                # terminal option). uid precondition: this drain kills
                # exactly the pod it LISTED — a same-name replacement
                # created in between (RC recreate racing a stale drain)
                # must survive.
                self.client.delete("pods", pod.metadata.name,
                                   pod.metadata.namespace,
                                   grace_period_seconds=0,
                                   uid=pod.metadata.uid or None)
                self.evictions_total += 1
                if self.recorder:
                    self.recorder.eventf(
                        pod, "Normal", "NodeControllerEviction",
                        "Marking for deletion Pod %s from Node %s",
                        pod.metadata.name, node_name)
            except NotFound:
                continue  # someone else deleted it: done is done
            except Conflict:
                continue  # uid moved: a replacement took the name —
                          # the pod this drain observed is gone
            except Exception:
                ok = False  # retried when the node drains again
        return ok

    # -- pod CIDR allocation ----------------------------------------------

    def reconcile_node_cidrs(self, nodes) -> None:
        """Assign a free /24 from the cluster CIDR to every node that
        lacks one (nodecontroller.go:476 reconcileNodeCIDRs). Unlike
        the reference — which regenerates len(nodes) candidate CIDRs
        each sync and pops from a random set — allocation here walks
        the subnets in address order, so assignments are deterministic
        and the pool isn't capped at the current node count."""
        import ipaddress
        used = {n.spec.pod_cidr for n in nodes if n.spec.pod_cidr}
        free = None  # lazy: the common case is every node assigned
        for node in nodes:
            if node.spec.pod_cidr:
                continue
            if free is None:
                cluster = ipaddress.ip_network(self.cluster_cidr)
                subnets = (cluster.subnets(new_prefix=24)
                           if cluster.prefixlen <= 24 else iter(()))
                free = (str(s) for s in subnets if str(s) not in used)
            cidr = next(free, None)
            if cidr is None:
                if self.recorder:
                    self.recorder.eventf(
                        node, "Normal", "CIDRNotAvailable",
                        "Node %s status is now: CIDRNotAvailable",
                        node.metadata.name)
                continue
            node.spec.pod_cidr = cidr
            try:
                self.client.update("nodes", node)
            except Exception:
                node.spec.pod_cidr = ""
                if self.recorder:
                    self.recorder.eventf(
                        node, "Normal", "CIDRAssignmentFailed",
                        "Node %s status is now: CIDRAssignmentFailed",
                        node.metadata.name)

    # -- control loop -----------------------------------------------------

    def monitor_once(self) -> None:
        try:
            nodes, _ = self.client.list("nodes")
        except Exception:
            return
        if self.allocate_node_cidrs:
            self.reconcile_node_cidrs(nodes)
        now = self.clock.now()
        names = {n.metadata.name for n in nodes}
        # deleted nodes: evict their pods (monitorNodeStatus :378-382)
        for gone in self._known_nodes - names:
            self._queue_eviction(gone)
            self._health.pop(gone, None)
        self._known_nodes = names

        observed = [(node, self._observe(node)) for node in nodes]

        # -- partition safety valve -----------------------------------
        # the whole fleet going NotReady/Unknown at once looks like a
        # master-side partition, not mass hardware death: halt all
        # evictions (queueing AND draining) until the unhealthy
        # fraction drops back under the threshold
        unhealthy = sum(1 for _, status in observed if status != "True")
        if (len(observed) >= self.partition_min_cluster
                and unhealthy > self.unhealthy_threshold * len(observed)):
            if not self.evictions_halted:
                self.evictions_halted = True
                self.partition_halts_total += 1
        elif self.evictions_halted:
            self.evictions_halted = False

        for node, status in observed:
            health = self._health[node.metadata.name]
            if status == "True":
                self._cancel_eviction(node.metadata.name)
            elif (now - health.ready_transition_timestamp
                  > self.pod_eviction_timeout):
                if self.evictions_halted:
                    continue
                if self._is_flapping(health, now):
                    self.flap_damped_total += 1
                    continue
                self._queue_eviction(node.metadata.name)
        if not self.evictions_halted:
            self._drain_eviction_queue()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.monitor_once()
            self._stop.wait(self.monitor_period)

    def run(self) -> "NodeController":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-controller")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
