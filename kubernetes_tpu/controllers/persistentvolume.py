"""PersistentVolume claim binder.

Reference: pkg/controller/persistentvolume/persistent_volume_claim_binder.go
— reconcile pending claims against available volumes: pick the smallest
volume whose capacity and access modes satisfy the claim, stamp
volume.spec.claimRef + phase Bound and claim.spec.volumeName + status
Bound; when a bound claim disappears the volume goes Released (Retain
reclaim policy keeps it for an admin; Recycle makes it Available again).
"""

from __future__ import annotations

import threading
from dataclasses import replace
from typing import List, Optional

from ..core import types as api
from ..core.errors import ApiError, NotFound
from ..core.quantity import Quantity

SYNC_PERIOD = 10.0  # ref: --pvclaimbinder-sync-period default 10s


def _storage(capacity) -> int:
    q = capacity.get("storage")
    return q.milli if q is not None else 0


def _access_ok(volume: api.PersistentVolume,
               claim: api.PersistentVolumeClaim) -> bool:
    return set(claim.spec.access_modes) <= set(volume.spec.access_modes)


def match_volume(claim: api.PersistentVolumeClaim,
                 volumes: List[api.PersistentVolume]
                 ) -> Optional[api.PersistentVolume]:
    """Smallest satisfying available volume (ref: volume index
    findBestMatchForClaim: exact-or-larger capacity, access mode subset)."""
    want = _storage(claim.spec.resources.requests)
    best = None
    for volume in volumes:
        if volume.spec.claim_ref is not None:
            continue
        if volume.status.phase not in ("", api.VOLUME_AVAILABLE):
            continue
        if not _access_ok(volume, claim):
            continue
        if _storage(volume.spec.capacity) < want:
            continue
        if best is None or (_storage(volume.spec.capacity)
                            < _storage(best.spec.capacity)):
            best = volume
    return best


class PersistentVolumeClaimBinder:
    def __init__(self, client, sync_period: float = SYNC_PERIOD):
        self.client = client
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- sync

    def sync_once(self) -> int:
        """Returns number of bind/release actions taken."""
        try:
            volumes, _ = self.client.list("persistentvolumes")
            claims, _ = self.client.list("persistentvolumeclaims")
        except Exception:
            return 0
        actions = 0
        claims_by_key = {(c.metadata.namespace, c.metadata.name): c
                         for c in claims}

        # phase volumes whose claim vanished; recycle if policy says so
        for volume in volumes:
            ref = volume.spec.claim_ref
            if ref is None:
                if volume.status.phase == "":
                    self._set_volume_phase(volume, api.VOLUME_AVAILABLE)
                    actions += 1
                continue
            if (ref.namespace, ref.name) not in claims_by_key:
                if volume.spec.persistent_volume_reclaim_policy == "Recycle":
                    scrubbed = replace(
                        volume,
                        spec=replace(volume.spec, claim_ref=None),
                        status=api.PersistentVolumeStatus(
                            phase=api.VOLUME_AVAILABLE))
                    self._update_volume(scrubbed)
                else:
                    self._set_volume_phase(volume, api.VOLUME_RELEASED)
                actions += 1
            elif volume.status.phase != api.VOLUME_BOUND:
                self._set_volume_phase(volume, api.VOLUME_BOUND)
                actions += 1

        # bind pending claims — against a fresh listing, since the phase
        # pass above bumped resource versions (stale objects would CAS-fail)
        if actions:
            try:
                volumes, _ = self.client.list("persistentvolumes")
            except Exception:
                return actions
        bound_refs = {(v.spec.claim_ref.namespace, v.spec.claim_ref.name):
                      v.metadata.name
                      for v in volumes if v.spec.claim_ref is not None}
        for claim in claims:
            key = (claim.metadata.namespace, claim.metadata.name)
            if claim.status.phase == api.CLAIM_BOUND:
                continue
            if key in bound_refs:
                # pre-bound volume (admin-set claimRef) or a crash between
                # volume and claim writes: finish from the volume's side
                self._mark_claim_bound(claim, bound_refs[key])
                actions += 1
                continue
            volume = match_volume(claim, volumes)
            if volume is None:
                if claim.status.phase != api.CLAIM_PENDING:
                    self._set_claim_phase(claim, api.CLAIM_PENDING)
                    actions += 1
                continue
            try:
                bound = replace(
                    volume,
                    spec=replace(volume.spec, claim_ref=api.ObjectReference(
                        kind="PersistentVolumeClaim",
                        namespace=claim.metadata.namespace,
                        name=claim.metadata.name,
                        uid=claim.metadata.uid)),
                    status=api.PersistentVolumeStatus(
                        phase=api.VOLUME_BOUND))
                self._update_volume(bound)
                # track locally so a later claim can't match this volume
                # this pass (store objects are never mutated in place)
                volumes[volumes.index(volume)] = bound
                bound_refs[key] = volume.metadata.name
                self._mark_claim_bound(claim, volume.metadata.name)
                actions += 1
            except ApiError:
                continue  # raced another binder; next resync converges
        return actions

    def _update_volume(self, volume: api.PersistentVolume) -> None:
        self.client.update("persistentvolumes", volume)

    def _set_volume_phase(self, volume: api.PersistentVolume,
                          phase: str) -> None:
        try:
            self.client.update_status("persistentvolumes", replace(
                volume, status=replace(volume.status, phase=phase)))
        except (NotFound, ApiError):
            pass

    def _mark_claim_bound(self, claim: api.PersistentVolumeClaim,
                          volume_name: str) -> None:
        try:
            if claim.spec.volume_name != volume_name:
                claim = self.client.update(
                    "persistentvolumeclaims",
                    replace(claim, spec=replace(claim.spec,
                                                volume_name=volume_name)),
                    claim.metadata.namespace)
            self.client.update_status("persistentvolumeclaims", replace(
                claim, status=api.PersistentVolumeClaimStatus(
                    phase=api.CLAIM_BOUND,
                    access_modes=list(claim.spec.access_modes))),
                claim.metadata.namespace)
        except (NotFound, ApiError):
            pass

    # -------------------------------------------------------- lifecycle

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.sync_once()
            self._stop.wait(self.sync_period)

    def run(self) -> "PersistentVolumeClaimBinder":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pv-claim-binder")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
