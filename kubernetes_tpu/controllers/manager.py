"""Controller manager: start every controller with one call.

Reference: cmd/kube-controller-manager/app/controllermanager.go:284-443 —
endpoints :284, RC manager :287, node controller :303, resourcequota
:327, namespace :351, HPA :368, daemonset :374, job :380, PV binder
:407, serviceaccount + tokens :433-443 (plus pod GC). Each controller is
independent; the manager only owns their lifecycle.
"""

from __future__ import annotations

from typing import List, Optional

from .daemon import DaemonSetController
from .deployment import DeploymentController
from .endpoint import EndpointsController
from .gc import PodGCController
from .job import JobController
from .namespace import NamespaceController
from .node import NodeController
from .persistentvolume import PersistentVolumeClaimBinder
from .podautoscaler import HorizontalController
from .replication import ReplicationManager
from .resourcequota import ResourceQuotaController
from .service import RouteController, ServiceController
from .serviceaccount import ServiceAccountsController, TokensController


class ControllerManager:
    def __init__(self, client, metrics_source=None, recorder=None,
                 pod_gc_threshold: int = 12500, cloud=None,
                 allocate_node_cidrs: bool = False,
                 cluster_cidr: str = "10.244.0.0/16"):
        self.controllers: List = [
            EndpointsController(client),
            ReplicationManager(client, recorder=recorder),
            NodeController(client, recorder=recorder,
                           allocate_node_cidrs=allocate_node_cidrs,
                           cluster_cidr=cluster_cidr),
            PodGCController(client, threshold=pod_gc_threshold),
            NamespaceController(client),
            ResourceQuotaController(client),
            JobController(client, recorder=recorder),
            DaemonSetController(client),
            DeploymentController(client),
            PersistentVolumeClaimBinder(client),
            ServiceAccountsController(client),
            TokensController(client),
        ]
        if metrics_source is not None:
            self.controllers.append(
                HorizontalController(client, metrics_source,
                                     recorder=recorder))
        if cloud is not None:
            self.controllers.append(ServiceController(client, cloud,
                                                      recorder=recorder))
            self.controllers.append(RouteController(
                client, cloud, cluster_cidr=cluster_cidr))

    def run(self) -> "ControllerManager":
        for c in self.controllers:
            c.run()
        return self

    def stop(self) -> None:
        for c in reversed(self.controllers):
            try:
                c.stop()
            except Exception:
                pass
