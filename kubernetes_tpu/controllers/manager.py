"""Controller manager: start every controller with one call.

Reference: cmd/kube-controller-manager/app/controllermanager.go:284-443 —
endpoints :284, RC manager :287, node controller :303, resourcequota
:327, namespace :351, HPA :368, daemonset :374, job :380, PV binder
:407, serviceaccount + tokens :433-443 (plus pod GC). Each controller is
independent; the manager only owns their lifecycle.

HA: pass `elect=LeaderElectionConfig(...)` and the manager becomes a
CANDIDATE — controllers are built and started only when its elector
wins the lease, and torn down when leadership is lost, so N replicas
run with exactly one acting controller-manager (the reference's
--leader-elect flag, forward-ported from its master election seam onto
the typed Lease; utils/leaderelection.py). Controllers are rebuilt
fresh on every leadership session: a re-elected manager re-lists
through its informers rather than trusting any pre-demotion carry.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from ..utils.leaderelection import LeaderElectionConfig, LeaderElector
from .daemon import DaemonSetController
from .deployment import DeploymentController
from .endpoint import EndpointsController
from .gc import PodGCController
from .job import JobController
from .namespace import NamespaceController
from .node import NodeController
from .persistentvolume import PersistentVolumeClaimBinder
from .podautoscaler import HorizontalController
from .replication import ReplicationManager
from .resourcequota import ResourceQuotaController
from .service import RouteController, ServiceController
from .serviceaccount import ServiceAccountsController, TokensController


class ControllerManager:
    def __init__(self, client, metrics_source=None, recorder=None,
                 pod_gc_threshold: int = 12500, cloud=None,
                 allocate_node_cidrs: bool = False,
                 cluster_cidr: str = "10.244.0.0/16",
                 elect: Optional[LeaderElectionConfig] = None):
        self._build_args = dict(
            client=client, metrics_source=metrics_source,
            recorder=recorder, pod_gc_threshold=pod_gc_threshold,
            cloud=cloud, allocate_node_cidrs=allocate_node_cidrs,
            cluster_cidr=cluster_cidr)
        self.controllers: List = []
        self.term = 0
        # serializes build/teardown against elector transitions
        self._lifecycle = threading.Lock()
        self.elector: Optional[LeaderElector] = None
        if elect is not None:
            self.elector = LeaderElector(
                client, elect,
                on_started_leading=self._on_started_leading,
                on_stopped_leading=self._stop_controllers)
        else:
            self.controllers = self._build()

    def _build(self) -> List:
        a = self._build_args
        client, recorder = a["client"], a["recorder"]
        controllers: List = [
            EndpointsController(client),
            ReplicationManager(client, recorder=recorder),
            NodeController(client, recorder=recorder,
                           allocate_node_cidrs=a["allocate_node_cidrs"],
                           cluster_cidr=a["cluster_cidr"]),
            PodGCController(client, threshold=a["pod_gc_threshold"]),
            NamespaceController(client),
            ResourceQuotaController(client),
            JobController(client, recorder=recorder),
            DaemonSetController(client),
            DeploymentController(client),
            PersistentVolumeClaimBinder(client),
            ServiceAccountsController(client),
            TokensController(client),
        ]
        if a["metrics_source"] is not None:
            controllers.append(
                HorizontalController(client, a["metrics_source"],
                                     recorder=recorder))
        if a["cloud"] is not None:
            controllers.append(ServiceController(client, a["cloud"],
                                                 recorder=recorder))
            controllers.append(RouteController(
                client, a["cloud"], cluster_cidr=a["cluster_cidr"]))
        return controllers

    # --------------------------------------------------- leadership hooks

    def _on_started_leading(self, term: int) -> None:
        """Fresh controllers per leadership session (see class doc);
        the fencing term rides on the instance for observability."""
        with self._lifecycle:
            self.term = term
            self.controllers = self._build()
            for c in self.controllers:
                c.run()

    def _stop_controllers(self) -> None:
        with self._lifecycle:
            for c in reversed(self.controllers):
                try:
                    c.stop()
                except Exception:
                    pass
            self.controllers = []

    # ------------------------------------------------------------- run

    @property
    def is_leader(self) -> bool:
        return self.elector is None or self.elector.is_leader

    def run(self) -> "ControllerManager":
        if self.elector is not None:
            self.elector.run()
        else:
            for c in self.controllers:
                c.run()
        return self

    def stop(self) -> None:
        if self.elector is not None:
            self.elector.stop()  # demotes -> _stop_controllers
        else:
            for c in reversed(self.controllers):
                try:
                    c.stop()
                except Exception:
                    pass

    def kill(self) -> None:
        """Simulated process death (chaos/crash.py): controllers halt
        and the lease is NOT released — the standby must wait out the
        expiry and take over under a new fencing term, exactly the
        wire a real crash leaves behind."""
        if self.elector is not None:
            self.elector.kill()
        # a dead process runs nothing: hard-stop the controller threads
        # (without the elector's clean on_stopped_leading semantics)
        self._stop_controllers()
