"""Terminated-pod garbage collector.

Reference: pkg/controller/gc/gc_controller.go — every gcCheckPeriod (20s)
list terminated pods (phase not Pending/Running/Unknown, via the negated
field selector :119-125); when the count exceeds the threshold, delete the
oldest by creationTimestamp (name as tie-break) down to the threshold
(:90-117). Threshold <= 0 disables GC (controllermanager
--terminated-pod-gc-threshold, default 12500)."""

from __future__ import annotations

import threading
from typing import Optional

from ..core.errors import NotFound
from ..utils.clock import Clock, RealClock

GC_CHECK_PERIOD = 20.0  # gc_controller.go:40
TERMINATED_SELECTOR = ("status.phase!=Pending,status.phase!=Running,"
                       "status.phase!=Unknown")  # :119-125


class PodGCController:
    def __init__(self, client, threshold: int = 12500,
                 check_period: float = GC_CHECK_PERIOD,
                 clock: Optional[Clock] = None):
        self.client = client
        self.threshold = threshold
        self.check_period = check_period
        self.clock = clock or RealClock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def gc_once(self) -> int:
        """Returns the number of pods deleted."""
        if self.threshold <= 0:
            return 0
        try:
            terminated, _ = self.client.list(
                "pods", field_selector=TERMINATED_SELECTOR)
        except Exception:
            return 0
        delete_count = len(terminated) - self.threshold
        if delete_count <= 0:
            return 0
        terminated.sort(key=lambda p: (p.metadata.creation_timestamp,
                                       p.metadata.name))
        deleted = 0
        for pod in terminated[:delete_count]:
            try:
                self.client.delete("pods", pod.metadata.name,
                                   pod.metadata.namespace)
                deleted += 1
            except NotFound:
                pass
            except Exception:
                pass  # transient; the pod is still terminated next tick
        return deleted

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.gc_once()
            except Exception:
                pass  # never let the gc thread die (util.Until semantics)
            self._stop.wait(self.check_period)

    def run(self) -> "PodGCController":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pod-gc")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
