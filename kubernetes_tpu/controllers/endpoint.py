"""Endpoints controller: service selector -> Endpoints subsets.

Reference: pkg/controller/endpoint/endpoints_controller.go syncService
(:253-380): for each service with a selector, list matching pods; each pod
with an IP contributes one address per service port (named targetPorts
resolve against container ports, findPort :403); ready pods land in
``addresses``, unready in ``not_ready_addresses``; subsets are repacked so
addresses sharing an identical port set merge (pkg/api/endpoints
RepackSubsets); no-op updates are skipped; a deleted service deletes its
Endpoints object.
"""

from __future__ import annotations

from dataclasses import replace
from typing import List, Optional, Tuple

from ..api.cache import Informer, meta_namespace_key
from ..core import types as api
from ..core.errors import NotFound
from ..core.labels import selector_from_set
from .framework import QueueWorkers, is_pod_ready


def find_port(pod: api.Pod, service_port: api.ServicePort) -> Optional[int]:
    """(endpoints_controller.go:403 findPort) int targetPort used as-is;
    str targetPort looked up among container port names; empty targetPort
    falls back to the service port number."""
    tp = service_port.target_port
    if isinstance(tp, int):
        return tp
    if isinstance(tp, str) and tp:
        for container in pod.spec.containers:
            for port in container.ports:
                if port.name == tp and port.protocol == \
                        (service_port.protocol or "TCP"):
                    return port.container_port
        return None
    return service_port.port or None


def repack_subsets(entries: List[Tuple[api.EndpointAddress, bool,
                                       api.EndpointPort]]
                   ) -> List[api.EndpointSubset]:
    """Merge addresses that share an identical port set
    (pkg/api/endpoints/util.go RepackSubsets)."""
    # per-address port accumulation first
    by_addr: dict = {}
    for addr, ready, port in entries:
        key = (addr.ip, addr.target_ref.name if addr.target_ref else "")
        rec = by_addr.setdefault(key, {"addr": addr, "ready": ready,
                                       "ports": []})
        rec["ports"].append(port)
    # group addresses by their full port set
    by_ports: dict = {}
    for rec in by_addr.values():
        pkey = tuple(sorted((p.name, p.port, p.protocol)
                            for p in rec["ports"]))
        grp = by_ports.setdefault(pkey, {"ports": rec["ports"],
                                         "ready": [], "unready": []})
        (grp["ready"] if rec["ready"] else grp["unready"]).append(rec["addr"])
    subsets = []
    for pkey in sorted(by_ports):
        grp = by_ports[pkey]
        subsets.append(api.EndpointSubset(
            addresses=sorted(grp["ready"], key=lambda a: a.ip),
            not_ready_addresses=sorted(grp["unready"], key=lambda a: a.ip),
            ports=sorted(grp["ports"],
                         key=lambda p: (p.name, p.port, p.protocol))))
    return subsets


class EndpointsController:
    def __init__(self, client, workers: int = 5):
        self.client = client
        self.workers = QueueWorkers(self._sync, workers, name="endpoints")
        self.service_informer = Informer(
            client, "services",
            on_add=lambda s: self.workers.enqueue(meta_namespace_key(s)),
            on_update=lambda o, s: self.workers.enqueue(
                meta_namespace_key(s)),
            on_delete=lambda s: self.workers.enqueue(meta_namespace_key(s)))
        self.pod_informer = Informer(
            client, "pods",
            on_add=self._pod_changed,
            on_update=lambda o, p: self._pod_changed(p, o),
            on_delete=self._pod_changed)

    def _pod_changed(self, pod: api.Pod,
                     old: Optional[api.Pod] = None) -> None:
        for svc in self.service_informer.cache.list():
            if svc.metadata.namespace != pod.metadata.namespace:
                continue
            if not svc.spec.selector:
                continue
            sel = selector_from_set(svc.spec.selector)
            if sel.matches(pod.metadata.labels) or (
                    old is not None and sel.matches(old.metadata.labels)):
                self.workers.enqueue(meta_namespace_key(svc))

    def _sync(self, key: str) -> None:
        svc = self.service_informer.cache.get_by_key(key)
        if svc is None:
            ns, _, name = key.rpartition("/")
            try:
                self.client.delete("endpoints", name, ns)
            except NotFound:
                pass
            return
        if not svc.spec.selector:
            return  # selector-less services get out-of-band endpoints

        sel = selector_from_set(svc.spec.selector)
        entries = []
        for pod in self.pod_informer.cache.list():
            if pod.metadata.namespace != svc.metadata.namespace:
                continue
            if not sel.matches(pod.metadata.labels):
                continue
            if not pod.status.pod_ip or \
                    pod.metadata.deletion_timestamp is not None:
                continue
            for sp in svc.spec.ports or [api.ServicePort()]:
                port_num = find_port(pod, sp)
                if port_num is None:
                    continue
                entries.append((
                    api.EndpointAddress(
                        ip=pod.status.pod_ip,
                        target_ref=api.ObjectReference(
                            kind="Pod", namespace=pod.metadata.namespace,
                            name=pod.metadata.name, uid=pod.metadata.uid)),
                    is_pod_ready(pod),
                    api.EndpointPort(name=sp.name, port=port_num,
                                     protocol=sp.protocol or "TCP")))
        subsets = repack_subsets(entries)

        try:
            current = self.client.get("endpoints", svc.metadata.name,
                                      svc.metadata.namespace)
        except NotFound:
            current = None
        if current is not None and current.subsets == subsets and \
                current.metadata.labels == svc.metadata.labels:
            return  # no-op skipped (syncService :365)
        if current is None:
            self.client.create("endpoints", api.Endpoints(
                metadata=api.ObjectMeta(name=svc.metadata.name,
                                        namespace=svc.metadata.namespace,
                                        labels=dict(svc.metadata.labels)),
                subsets=subsets), svc.metadata.namespace)
        else:
            self.client.update("endpoints", replace(
                current, subsets=subsets,
                metadata=replace(current.metadata,
                                 labels=dict(svc.metadata.labels))),
                svc.metadata.namespace)

    def run(self) -> "EndpointsController":
        self.service_informer.start()
        self.pod_informer.start()
        self.workers.start()
        return self

    def stop(self) -> None:
        self.workers.stop()
        self.service_informer.stop()
        self.pod_informer.stop()
