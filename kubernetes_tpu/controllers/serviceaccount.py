"""ServiceAccount + token controllers.

Reference: pkg/serviceaccount/serviceaccounts_controller.go (ensure every
namespace carries a "default" ServiceAccount) and tokens_controller.go
(mint a token Secret per ServiceAccount and reference it from
sa.secrets). Wired from controllermanager.go:433-443.
"""

from __future__ import annotations

import secrets as pysecrets
import threading
from dataclasses import replace
from typing import Optional

from ..api.cache import Informer
from ..core import types as api
from ..core.errors import ApiError, NotFound

DEFAULT_SA = "default"
TOKEN_SECRET_TYPE = "kubernetes.io/service-account-token"


class ServiceAccountsController:
    """Every active namespace gets the default ServiceAccount."""

    def __init__(self, client):
        self.client = client
        self.ns_informer = Informer(
            client, "namespaces",
            on_add=self._ensure_default,
            on_update=lambda old, new: self._ensure_default(new))
        self.sa_informer = Informer(
            client, "serviceaccounts",
            on_delete=self._sa_deleted)

    def _ensure_default(self, ns: api.Namespace) -> None:
        if ns.status.phase != "Active":
            return
        try:
            self.client.get("serviceaccounts", DEFAULT_SA, ns.metadata.name)
        except NotFound:
            try:
                self.client.create("serviceaccounts", api.ServiceAccount(
                    metadata=api.ObjectMeta(name=DEFAULT_SA,
                                            namespace=ns.metadata.name)),
                    ns.metadata.name)
            except ApiError:
                pass  # raced or namespace terminating
        except ApiError:
            pass

    def _sa_deleted(self, sa: api.ServiceAccount) -> None:
        # recreate the default SA if it goes away (the reference re-syncs
        # the namespace on SA deletion)
        if sa.metadata.name != DEFAULT_SA:
            return
        try:
            ns = self.client.get("namespaces", sa.metadata.namespace)
        except (NotFound, ApiError):
            return
        self._ensure_default(ns)

    def run(self) -> "ServiceAccountsController":
        self.ns_informer.start()
        self.sa_informer.start()
        return self

    def stop(self) -> None:
        self.ns_informer.stop()
        self.sa_informer.stop()


class TokensController:
    """Mint a token Secret per ServiceAccount and link it."""

    def __init__(self, client):
        self.client = client
        self.sa_informer = Informer(
            client, "serviceaccounts",
            on_add=self._ensure_token,
            on_update=lambda old, new: self._ensure_token(new))

    def _token_name(self, sa: api.ServiceAccount) -> str:
        return f"{sa.metadata.name}-token"

    def _ensure_token(self, sa: api.ServiceAccount) -> None:
        name = self._token_name(sa)
        try:
            self.client.get("secrets", name, sa.metadata.namespace)
            have_secret = True
        except NotFound:
            have_secret = False
        except ApiError:
            return
        if not have_secret:
            secret = api.Secret(
                metadata=api.ObjectMeta(
                    name=name, namespace=sa.metadata.namespace,
                    annotations={"kubernetes.io/service-account.name":
                                 sa.metadata.name}),
                type=TOKEN_SECRET_TYPE,
                data={"token": pysecrets.token_urlsafe(32)})
            try:
                self.client.create("secrets", secret, sa.metadata.namespace)
            except ApiError:
                return
        if not any(ref.name == name for ref in sa.secrets):
            try:
                fresh = self.client.get("serviceaccounts", sa.metadata.name,
                                        sa.metadata.namespace)
                if any(ref.name == name for ref in fresh.secrets):
                    return
                self.client.update(
                    "serviceaccounts",
                    replace(fresh, secrets=list(fresh.secrets)
                            + [api.ObjectReference(kind="Secret",
                                                   name=name)]),
                    sa.metadata.namespace)
            except (NotFound, ApiError):
                pass

    def run(self) -> "TokensController":
        self.sa_informer.start()
        return self

    def stop(self) -> None:
        self.sa_informer.stop()
