"""ReplicationController manager.

Reference: pkg/controller/replication/replication_controller.go —
syncReplicationController (:401-446), manageReplicas (:339-396, burst cap
500, delete-preference sort, per-failure expectation rollback),
getPodController overlap resolution by oldest creationTimestamp (:203-219),
pod events adjusting expectations (addPod/updatePod/deletePod :221-280).
"""

from __future__ import annotations

import threading
from dataclasses import replace
from typing import List, Optional

from ..api.cache import Informer, meta_namespace_key
from ..core import types as api
from ..core.labels import selector_from_set
from .framework import (ControllerExpectations, QueueWorkers,
                        active_pods_sort_key, filter_active_pods)

BURST_REPLICAS = 500  # replication_controller.go:64


class ReplicationManager:
    def __init__(self, client, burst_replicas: int = BURST_REPLICAS,
                 workers: int = 5, recorder=None):
        self.client = client
        self.burst_replicas = burst_replicas
        self.recorder = recorder
        self.expectations = ControllerExpectations()
        self.workers = QueueWorkers(self._sync, workers, name="rc-manager")
        # resync re-drives every RC periodically: "next sync retries"
        # in _update_status is a lie without it — a status write that
        # failed after the last pod event (e.g. under injected API
        # faults) would otherwise leave status.replicas stale forever,
        # wedging any controller layered on RC status (the Deployment
        # rollout waits on old-RC status reaching 0; the trace replay
        # shook this out). The reference runs the RC manager on a full
        # resync for the same reason.
        self.rc_informer = Informer(
            client, "replicationcontrollers",
            on_add=self._enqueue_rc,
            on_update=lambda old, new: self._enqueue_rc(new),
            on_delete=self._delete_rc,
            resync_period=5.0)
        self.pod_informer = Informer(
            client, "pods",
            on_add=self._add_pod, on_update=self._update_pod,
            on_delete=self._delete_pod)

    # -- event handlers ---------------------------------------------------

    def _enqueue_rc(self, rc: api.ReplicationController) -> None:
        self.workers.enqueue(meta_namespace_key(rc))

    def _delete_rc(self, rc: api.ReplicationController) -> None:
        key = meta_namespace_key(rc)
        self.expectations.delete(key)
        self.workers.enqueue(key)

    def _pod_controller(self, pod: api.Pod
                        ) -> Optional[api.ReplicationController]:
        """Oldest matching RC wins on overlap
        (replication_controller.go:203-219)."""
        matching = [
            rc for rc in self.rc_informer.cache.list()
            if rc.metadata.namespace == pod.metadata.namespace
            and rc.spec.selector
            and selector_from_set(rc.spec.selector).matches(
                pod.metadata.labels)]
        if not matching:
            return None
        matching.sort(key=lambda rc: (rc.metadata.creation_timestamp,
                                      rc.metadata.name))
        return matching[0]

    def _add_pod(self, pod: api.Pod) -> None:
        rc = self._pod_controller(pod)
        if rc is None:
            return
        self.expectations.creation_observed(meta_namespace_key(rc))
        self._enqueue_rc(rc)

    def _update_pod(self, old: api.Pod, pod: api.Pod) -> None:
        rc = self._pod_controller(pod)
        if rc is not None:
            self._enqueue_rc(rc)
        if old.metadata.labels != pod.metadata.labels:
            old_rc = self._pod_controller(old)
            if old_rc is not None and (rc is None or
                                       old_rc.metadata.name !=
                                       rc.metadata.name):
                self._enqueue_rc(old_rc)

    def _delete_pod(self, pod: api.Pod) -> None:
        rc = self._pod_controller(pod)
        if rc is None:
            return
        self.expectations.deletion_observed(meta_namespace_key(rc))
        self._enqueue_rc(rc)

    # -- sync -------------------------------------------------------------

    def _rc_pods(self, rc: api.ReplicationController) -> List[api.Pod]:
        sel = selector_from_set(rc.spec.selector)
        return [p for p in self.pod_informer.cache.list()
                if p.metadata.namespace == rc.metadata.namespace
                and sel.matches(p.metadata.labels)]

    def _sync(self, key: str) -> None:
        rc = self.rc_informer.cache.get_by_key(key)
        if rc is None:
            self.expectations.delete(key)
            return
        filtered = filter_active_pods(self._rc_pods(rc))
        if self.expectations.satisfied(key):
            self._manage_replicas(filtered, rc)
        self._update_status(rc, len(filtered))

    def _manage_replicas(self, filtered: List[api.Pod],
                         rc: api.ReplicationController) -> None:
        key = meta_namespace_key(rc)
        diff = len(filtered) - rc.spec.replicas
        if diff < 0:
            diff = min(-diff, self.burst_replicas)
            self.expectations.expect_creations(key, diff)
            self._spawn_all([lambda: self._create_pod(rc, key)] * diff)
        elif diff > 0:
            diff = min(diff, self.burst_replicas)
            self.expectations.expect_deletions(key, diff)
            if rc.spec.replicas != 0:
                filtered = sorted(filtered, key=active_pods_sort_key)
            self._spawn_all([
                (lambda p: lambda: self._delete_one(rc, key, p))(pod)
                for pod in filtered[:diff]])

    @staticmethod
    def _spawn_all(fns) -> None:
        # the reference fans these out on goroutines + WaitGroup
        # (manageReplicas :352-365); cheap threads keep latency flat for
        # large diffs against an HTTP apiserver
        threads = [threading.Thread(target=fn, daemon=True) for fn in fns]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _create_pod(self, rc: api.ReplicationController, key: str) -> None:
        tpl = rc.spec.template
        pod = api.Pod(
            metadata=api.ObjectMeta(
                generate_name=f"{rc.metadata.name}-",
                namespace=rc.metadata.namespace,
                labels=dict(tpl.metadata.labels),
                annotations={
                    "kubernetes.io/created-by":
                        f"ReplicationController/{rc.metadata.name}"}),
            spec=tpl.spec,
            status=api.PodStatus(phase="Pending"))
        try:
            self.client.create("pods", pod, rc.metadata.namespace)
            if self.recorder:
                self.recorder.eventf(rc, "Normal", "SuccessfulCreate",
                                     "Created pod")
        except Exception:
            # informer will never observe this pod: roll back expectation
            self.expectations.creation_observed(key)
            if self.recorder:
                self.recorder.eventf(rc, "Warning", "FailedCreate",
                                     "Error creating pod")

    def _delete_one(self, rc: api.ReplicationController, key: str,
                    pod: api.Pod) -> None:
        try:
            self.client.delete("pods", pod.metadata.name,
                               pod.metadata.namespace)
            if self.recorder:
                self.recorder.eventf(rc, "Normal", "SuccessfulDelete",
                                     "Deleted pod %s", pod.metadata.name)
        except Exception:
            self.expectations.deletion_observed(key)
            if self.recorder:
                self.recorder.eventf(rc, "Warning", "FailedDelete",
                                     "Error deleting pod %s",
                                     pod.metadata.name)

    def _update_status(self, rc: api.ReplicationController,
                       num_replicas: int) -> None:
        """(replication_controller.go updateReplicaCount retry loop)"""
        if rc.status.replicas == num_replicas:
            return
        try:
            fresh = self.client.get("replicationcontrollers",
                                    rc.metadata.name, rc.metadata.namespace)
            updated = replace(fresh, status=replace(
                fresh.status, replicas=num_replicas,
                observed_generation=fresh.metadata.generation))
            self.client.update_status("replicationcontrollers", updated,
                                      rc.metadata.namespace)
        except Exception:
            pass  # next sync retries

    # -- lifecycle --------------------------------------------------------

    def run(self) -> "ReplicationManager":
        self.rc_informer.start()
        self.pod_informer.start()
        self.workers.start()
        return self

    def stop(self) -> None:
        self.workers.stop()
        self.rc_informer.stop()
        self.pod_informer.stop()
