"""Shared controller machinery: expectations, worker pools, pod filters.

Reference: pkg/controller/controller_utils.go — ControllerExpectations
(:98-190), ActivePods delete-preference sort (:377-398),
FilterActivePods (:400-410)."""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

from ..core import types as api
from ..utils.clock import Clock, RealClock
from ..utils.workqueue import WorkQueue

EXPECTATIONS_TIMEOUT = 5 * 60.0  # controller_utils.go ExpectationsTimeout


class _Expectation:
    __slots__ = ("add", "dels", "timestamp")

    def __init__(self, add: int, dels: int, timestamp: float):
        self.add = add
        self.dels = dels
        self.timestamp = timestamp

    def fulfilled(self) -> bool:
        return self.add <= 0 and self.dels <= 0


class ControllerExpectations:
    """Tracks in-flight creates/deletes per controller so a sync doesn't
    act on a stale cache (controller_utils.go:98-190). Semantics kept:
    absent or expired expectations mean "sync away" (SatisfiedExpectations
    returns true when no record exists, :135-156)."""

    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or RealClock()
        self._store: Dict[str, _Expectation] = {}
        self._lock = threading.Lock()

    def satisfied(self, key: str) -> bool:
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                return True
            if self.clock.now() - exp.timestamp > EXPECTATIONS_TIMEOUT:
                return True
            return exp.fulfilled()

    def set(self, key: str, add: int, dels: int) -> None:
        with self._lock:
            self._store[key] = _Expectation(add, dels, self.clock.now())

    def expect_creations(self, key: str, adds: int) -> None:
        self.set(key, adds, 0)

    def expect_deletions(self, key: str, dels: int) -> None:
        self.set(key, 0, dels)

    def creation_observed(self, key: str) -> None:
        self._lower(key, add=1)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, dels=1)

    def _lower(self, key: str, add: int = 0, dels: int = 0) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is not None:
                exp.add -= add
                exp.dels -= dels

    def delete(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)


class QueueWorkers:
    """N worker threads draining a WorkQueue into a sync handler — the
    reference's `go util.Until(rm.worker, ...)` loop
    (replication_controller.go:322-336). The queue guarantees one key is
    never processed concurrently. A sync that raises is requeued with
    per-key exponential backoff (no informer resync exists to re-drive a
    dropped key)."""

    def __init__(self, sync: Callable[[str], None], workers: int = 5,
                 name: str = "controller",
                 retry_initial: float = 0.05, retry_max: float = 5.0):
        self.queue = WorkQueue()
        self.sync = sync
        self.workers = workers
        self.name = name
        self.retry_initial = retry_initial
        self.retry_max = retry_max
        self._retry_delay: Dict[str, float] = {}
        self._threads: List[threading.Thread] = []

    def enqueue(self, key: str) -> None:
        self.queue.add(key)

    def start(self) -> "QueueWorkers":
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"{self.name}-{i}")
            t.start()
            self._threads.append(t)
        return self

    def _retry_later(self, key: str) -> None:
        delay = self._retry_delay.get(key, self.retry_initial)
        self._retry_delay[key] = min(delay * 2, self.retry_max)
        timer = threading.Timer(delay, lambda: self.queue.add(key))
        timer.daemon = True
        timer.start()

    def _worker(self) -> None:
        while True:
            key = self.queue.get()
            if key is None:
                return
            try:
                self.sync(key)
                self._retry_delay.pop(key, None)
            except Exception:
                self._retry_later(key)
            finally:
                self.queue.done(key)

    def stop(self) -> None:
        self.queue.shutdown()


def filter_active_pods(pods: Sequence[api.Pod]) -> List[api.Pod]:
    """(controller_utils.go:400 FilterActivePods)"""
    return [p for p in pods
            if p.status.phase not in ("Succeeded", "Failed")
            and p.metadata.deletion_timestamp is None]


def is_pod_ready(pod: api.Pod) -> bool:
    return any(c.type == "Ready" and c.status == "True"
               for c in pod.status.conditions)


_PHASE_RANK = {"Pending": 0, "Unknown": 1, "Running": 2}


def active_pods_sort_key(pod: api.Pod):
    """Delete-preference order: unassigned < assigned, Pending < Unknown
    < Running, not-ready < ready (controller_utils.go:383-398)."""
    return (0 if not pod.spec.node_name else 1,
            _PHASE_RANK.get(pod.status.phase, 1),
            1 if is_pod_ready(pod) else 0)
