"""Deployment controller: declarative rollouts over ReplicationControllers.

Reference: pkg/controller/deployment/deployment_controller.go (v1.1) —
a Deployment owns RCs distinguished by a pod-template hash label
(getNewRC/getOldRCs); RollingUpdate reconciliation scales the new RC up
(bounded by maxSurge) and old RCs down (bounded by maxUnavailable) until
the new RC carries spec.replicas; Recreate scales old RCs to zero first.
The RC manager (replication.py) does the actual pod management — this
controller only moves RC replica counts, exactly the reference's
division of labor.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import replace
from typing import List, Optional, Tuple

from ..api.cache import Informer, meta_namespace_key
from ..core import types as api
from ..core.intstr import resolve_int_or_percent
from ..core.labels import selector_from_set
from ..core.serde import to_wire
from .framework import QueueWorkers


def pod_template_hash(template: api.PodTemplateSpec) -> str:
    """Deterministic hash of the template (the reference hashes the
    api.PodTemplateSpec with adler32; any stable digest serves)."""
    wire = json.dumps(to_wire(template), sort_keys=True)
    return hashlib.sha1(wire.encode()).hexdigest()[:10]


class DeploymentController:
    def __init__(self, client, workers: int = 5):
        self.client = client
        self.workers = QueueWorkers(self._sync, workers, name="deployment")
        # resync re-drives every deployment periodically: rollout
        # progress can hinge on POD readiness, which produces no event
        # on the deployments (or even RC) watch — edge-triggering alone
        # deadlocks mid-rollout (the reference runs this controller on
        # a 30s full resync for the same reason)
        self.deploy_informer = Informer(
            client, "deployments",
            on_add=self._enqueue,
            on_update=lambda old, new: self._enqueue(new),
            on_delete=self._enqueue,
            resync_period=5.0)
        self.rc_informer = Informer(
            client, "replicationcontrollers",
            on_add=self._enqueue_rc_deployment,
            on_update=lambda old, new: self._enqueue_rc_deployment(new),
            on_delete=self._enqueue_rc_deployment)

    def _enqueue(self, d: api.Deployment) -> None:
        self.workers.enqueue(meta_namespace_key(d))

    def _enqueue_rc_deployment(self, rc: api.ReplicationController) -> None:
        for d in self.deploy_informer.cache.list():
            if d.metadata.namespace != rc.metadata.namespace:
                continue
            if d.spec.selector and selector_from_set(
                    d.spec.selector).matches(rc.spec.template.metadata.labels
                                             if rc.spec.template else {}):
                self._enqueue(d)

    # ----------------------------------------------------------- sync

    def _deployment_rcs(self, d: api.Deployment
                        ) -> Tuple[Optional[api.ReplicationController],
                                   List[api.ReplicationController]]:
        """(new_rc, old_rcs) split by template hash (getNewRC/getOldRCs).

        Listed LIVE through the client, not the informer cache: the sync
        itself creates RCs, and acting on a cache that hasn't observed
        them yet would create duplicates every pass (the v1.1 reference
        also lists RCs through the client in its sync). The informer only
        drives enqueues."""
        hash_key = d.spec.unique_label_key
        _, want = self._hashed_template(d)
        matches: List[api.ReplicationController] = []
        old: List[api.ReplicationController] = []
        sel = selector_from_set(d.spec.selector)
        rcs, _ = self.client.list("replicationcontrollers",
                                  d.metadata.namespace)
        for rc in rcs:
            tpl_labels = (rc.spec.template.metadata.labels
                          if rc.spec.template else {})
            if not sel.matches(tpl_labels):
                continue
            if tpl_labels.get(hash_key) == want:
                matches.append(rc)
            else:
                old.append(rc)
        if not matches:
            return None, old
        # oldest same-hash RC is THE new RC; duplicates (from a crashed
        # sync or racing controllers) drain like old RCs
        matches.sort(key=lambda rc: (rc.metadata.creation_timestamp,
                                     rc.metadata.name))
        return matches[0], old + matches[1:]

    def _hashed_template(self, d: api.Deployment):
        """-> (template carrying the hash label, digest). The digest is of
        the BASE template (hash label stripped) — the same value the label
        stores, so lookups and creation agree (deployment_controller.go
        getNewRC: the RC's selector and template carry podTemplateHash)."""
        tpl = d.spec.template
        labels = dict(tpl.metadata.labels)
        labels.pop(d.spec.unique_label_key, None)
        base = api.PodTemplateSpec(
            metadata=replace(tpl.metadata, labels=labels), spec=tpl.spec)
        digest = pod_template_hash(base)
        labels = dict(labels)
        labels[d.spec.unique_label_key] = digest
        return api.PodTemplateSpec(
            metadata=replace(tpl.metadata, labels=labels),
            spec=tpl.spec), digest

    def _sync(self, key: str) -> None:
        d = self.deploy_informer.cache.get_by_key(key)
        if d is None:
            return
        try:
            new_rc, old_rcs = self._deployment_rcs(d)
        except Exception:
            return  # apiserver hiccup: informer events re-drive
        if new_rc is None:
            new_rc = self._create_new_rc(d)
            if new_rc is None:
                return
        if (d.spec.strategy or api.DeploymentStrategy()).type == "Recreate":
            for rc in old_rcs:
                if rc.spec.replicas != 0:
                    self._scale(rc, 0)
            if all(rc.status.replicas == 0 for rc in old_rcs):
                if new_rc.spec.replicas != d.spec.replicas:
                    self._scale(new_rc, d.spec.replicas)
        else:
            self._rolling_update(d, new_rc, old_rcs)
        self._cleanup_and_status(d, new_rc, old_rcs)

    def _rolling_update(self, d: api.Deployment,
                        new_rc: api.ReplicationController,
                        old_rcs: List[api.ReplicationController]) -> None:
        """(reconcileNewRC/reconcileOldRCs: surge and unavailable bounds)"""
        strategy = d.spec.strategy or api.DeploymentStrategy()
        ru = strategy.rolling_update or api.RollingUpdateDeployment()
        max_surge = resolve_int_or_percent(ru.max_surge, d.spec.replicas)
        max_unavailable = resolve_int_or_percent(ru.max_unavailable,
                                                 d.spec.replicas)
        old_total = sum(rc.spec.replicas for rc in old_rcs)
        total = new_rc.spec.replicas + old_total
        max_total = d.spec.replicas + max_surge
        min_available = d.spec.replicas - max_unavailable

        if new_rc.spec.replicas < d.spec.replicas and total < max_total:
            grow = min(d.spec.replicas - new_rc.spec.replicas,
                       max_total - total)
            self._scale(new_rc, new_rc.spec.replicas + grow)
        elif new_rc.spec.replicas > d.spec.replicas:
            # deployment scaled down: the new RC tracks spec directly
            # (reconcileNewRC's scale-down branch)
            self._scale(new_rc, d.spec.replicas)
        # availability means READY pods, not active pod count — scaling
        # old RCs down against status.replicas would count the new RC's
        # still-unready surge pods as available and let a rollout with
        # maxUnavailable=0 delete every ready old pod before a single
        # new one passes readiness (reconcileOldRCs scales by
        # GetAvailablePodsForRCs, deployment/deployment.go)
        # both counts share ONE pod snapshot: a deletion landing
        # between two separate LISTs would inflate the removal budget
        snapshot: dict = {}
        available = self._ready_pod_count([new_rc] + list(old_rcs),
                                          snapshot)
        # deletions already scheduled but not yet executed (a prior
        # sync shrank an old RC whose manager hasn't killed the pod
        # yet) still read as available — budget them as spent, or two
        # back-to-back syncs double-delete past the maxUnavailable
        # floor (the availability-gate test catches this race)
        pending_deletes = max(0, self._ready_pod_count(old_rcs, snapshot)
                              - old_total)
        can_remove = available - pending_deletes - min_available
        for rc in sorted(old_rcs, key=lambda r: (r.metadata.creation_timestamp,
                                                 r.metadata.name)):
            if can_remove <= 0:
                break
            if rc.spec.replicas == 0:
                continue
            shrink = min(rc.spec.replicas, can_remove)
            self._scale(rc, rc.spec.replicas - shrink)
            can_remove -= shrink

    def _ready_pod_count(self, rcs, by_ns: Optional[dict] = None) -> int:
        """Ready pods across the RCs' selectors (the reference's
        GetAvailablePodsForRCs, minus minReadySeconds which v1.1's
        Deployment does not surface). TERMINATING pods are excluded: a
        pod whose deletion has started still reports Ready until its
        kubelet tears it down, and counting it would let the rollout
        scale old RCs below the maxUnavailable floor (the trace
        replay's availability gate caught exactly this)."""
        from .framework import is_pod_ready
        counted = set()
        total = 0
        by_ns = {} if by_ns is None else by_ns
        for rc in rcs:
            ns = rc.metadata.namespace
            if ns not in by_ns:
                try:
                    by_ns[ns], _ = self.client.list("pods", ns)
                except Exception:
                    by_ns[ns] = []
            sel = selector_from_set(rc.spec.selector)
            for pod in by_ns[ns]:
                key = (ns, pod.metadata.name)
                if key in counted:
                    continue
                if (pod.metadata.deletion_timestamp is None
                        and sel.matches(pod.metadata.labels)
                        and is_pod_ready(pod)):
                    counted.add(key)
                    total += 1
        return total

    def _create_new_rc(self, d: api.Deployment
                       ) -> Optional[api.ReplicationController]:
        tpl, digest = self._hashed_template(d)
        selector = dict(d.spec.selector)
        selector[d.spec.unique_label_key] = digest
        rc = api.ReplicationController(
            metadata=api.ObjectMeta(
                generate_name=f"{d.metadata.name}-",
                namespace=d.metadata.namespace,
                labels=dict(tpl.metadata.labels),
                annotations={"kubernetes.io/created-by":
                             f"Deployment/{d.metadata.name}"}),
            spec=api.ReplicationControllerSpec(
                replicas=0,  # rolled up by the rolling-update loop
                selector=selector, template=tpl))
        try:
            return self.client.create("replicationcontrollers", rc,
                                      d.metadata.namespace)
        except Exception:
            return None

    def _scale(self, rc: api.ReplicationController, replicas: int) -> None:
        try:
            fresh = self.client.get("replicationcontrollers",
                                    rc.metadata.name, rc.metadata.namespace)
            self.client.update(
                "replicationcontrollers",
                replace(fresh, spec=replace(fresh.spec, replicas=replicas)),
                rc.metadata.namespace)
        except Exception:
            pass  # next sync retries

    def _cleanup_and_status(self, d: api.Deployment,
                            new_rc: api.ReplicationController,
                            old_rcs: List[api.ReplicationController]) -> None:
        # drained old RCs are deleted (cleanupOldRCs)
        for rc in old_rcs:
            if rc.spec.replicas == 0 and rc.status.replicas == 0:
                try:
                    self.client.delete("replicationcontrollers",
                                       rc.metadata.name,
                                       rc.metadata.namespace)
                except Exception:
                    pass
        total = (new_rc.status.replicas
                 + sum(rc.status.replicas for rc in old_rcs))
        # surfaced for the rollout availability gate (the trace replay
        # asserts the rolling-update invariant off these fields):
        # available counts READY pods, unavailable the gap to the
        # larger of desired and present totals
        available = self._ready_pod_count([new_rc] + list(old_rcs))
        unavailable = max(0, max(d.spec.replicas, total) - available)
        if (d.status.replicas == total
                and d.status.updated_replicas == new_rc.status.replicas
                and d.status.available_replicas == available
                and d.status.unavailable_replicas == unavailable):
            return
        try:
            self.client.update_status("deployments", replace(
                d, status=api.DeploymentStatus(
                    replicas=total,
                    updated_replicas=new_rc.status.replicas,
                    available_replicas=available,
                    unavailable_replicas=unavailable,
                    observed_generation=d.metadata.generation)),
                d.metadata.namespace)
        except Exception:
            pass

    def run(self) -> "DeploymentController":
        self.deploy_informer.start()
        self.rc_informer.start()
        self.workers.start()
        return self

    def stop(self) -> None:
        self.workers.stop()
        self.deploy_informer.stop()
        self.rc_informer.stop()
