"""ResourceQuota controller: periodic usage recalculation.

Reference: pkg/controller/resourcequota/resource_quota_controller.go —
every full-resync period, recompute each quota's status.used from the live
objects in its namespace and write it back when it drifted. This is the
decrement path: admission (admission/plugins.py ResourceQuota) only ever
increments used; deletes are reconciled here, exactly like the
reference's controller-resync division of labor.

Terminated pods don't count (the reference skips Succeeded/Failed pods in
its pod usage calculation), so pod churn can't exhaust a namespace.
"""

from __future__ import annotations

import threading
from dataclasses import replace
from typing import Dict, Optional

from ..core import types as api
from ..core.errors import ApiError
from ..core.quantity import Quantity

FULL_RESYNC_PERIOD = 10.0  # ref default --resource-quota-sync-period=10s

COUNTED_RESOURCES = ("pods", "services", "replicationcontrollers",
                     "secrets", "resourcequotas")


def calculate_usage(client, quota: api.ResourceQuota) -> Dict[str, Quantity]:
    """Live usage for every resource the quota bounds (milli units)."""
    ns = quota.metadata.namespace
    hard = quota.spec.hard
    used: Dict[str, Quantity] = {}
    pods = None
    if {"pods", "cpu", "memory"} & set(hard):
        all_pods, _ = client.list("pods", ns)
        pods = [p for p in all_pods
                if p.status.phase not in (api.POD_SUCCEEDED, api.POD_FAILED)]
    if "pods" in hard:
        used["pods"] = Quantity(1000 * len(pods))
    if "cpu" in hard or "memory" in hard:
        from ..admission.plugins import pod_usage
        cpu = 0
        mem = 0
        for p in pods:
            u = pod_usage(p)
            cpu += u["cpu"]
            mem += u["memory"]
        if "cpu" in hard:
            used["cpu"] = Quantity(cpu)
        if "memory" in hard:
            used["memory"] = Quantity(mem)
    for resource in COUNTED_RESOURCES:
        if resource in ("pods",) or resource not in hard:
            continue
        items, _ = client.list(resource, ns)
        used[resource] = Quantity(1000 * len(items))
    return used


class ResourceQuotaController:
    def __init__(self, client, sync_period: float = FULL_RESYNC_PERIOD):
        self.client = client
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sync_once(self) -> int:
        """Recalculate every quota; returns how many were rewritten."""
        try:
            quotas, _ = self.client.list("resourcequotas")
        except Exception:
            return 0
        rewritten = 0
        for quota in quotas:
            try:
                used = calculate_usage(self.client, quota)
            except Exception:
                continue
            current = {k: v for k, v in quota.status.used.items()}
            if current == used and dict(quota.status.hard) == dict(
                    quota.spec.hard):
                continue
            updated = replace(quota, status=api.ResourceQuotaStatus(
                hard=dict(quota.spec.hard), used=used))
            try:
                self.client.update_status("resourcequotas", updated,
                                          quota.metadata.namespace)
                rewritten += 1
            except ApiError:
                pass  # raced with admission's CAS increment: next resync
        return rewritten

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.sync_once()
            self._stop.wait(self.sync_period)

    def run(self) -> "ResourceQuotaController":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="resourcequota-controller")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
