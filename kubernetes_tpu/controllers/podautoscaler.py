"""Horizontal pod autoscaler.

Reference: pkg/controller/podautoscaler/horizontal.go — every sync period
(default 30s) compute desired replicas from observed CPU utilization vs
the target: desired = ceil(current * actual/target), with a 10% tolerance
band, clamped to [min, max]; scale the referenced RC. The reference reads
utilization from heapster; here the metrics source is injectable
(fn(namespace, selector_labels) -> average utilization percent or None),
with the same semantics: no metrics -> no scaling.

Downscale stabilization (the later reference's
--horizontal-pod-autoscaler-downscale-stabilization, backported for the
trace-replay soak): with a window of N seconds, the effective desired
count is the MAX recommendation over the last N seconds — upscales act
immediately, downscales only once every recommendation in the window
agrees. A diurnal replay's metric dips then stop flapping replica
counts (tests/test_workload_controllers.py pins flap vs genuine
ramp-down)."""

from __future__ import annotations

import math
import threading
from dataclasses import replace
from typing import Callable, Dict, List, Optional, Tuple

from ..core import types as api
from ..core.errors import ApiError, NotFound
from ..utils.clock import Clock, RealClock

SYNC_PERIOD = 30.0        # horizontal.go default --horizontal-pod-autoscaler-sync-period
TOLERANCE = 0.1           # horizontal.go tolerance

MetricsSource = Callable[[str, Dict[str, str]], Optional[float]]


class HorizontalController:
    def __init__(self, client, metrics: MetricsSource,
                 sync_period: float = SYNC_PERIOD, recorder=None,
                 downscale_stabilization: float = 0.0,
                 clock: Optional[Clock] = None):
        self.client = client
        self.metrics = metrics
        self.recorder = recorder
        self.sync_period = sync_period
        self.downscale_stabilization = downscale_stabilization
        self.clock = clock or RealClock()
        # per-HPA (ns/name) recommendation history inside the window
        self._recommendations: Dict[str, List[Tuple[float, int]]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def reconcile_once(self) -> int:
        """Sync every HPA; returns how many scaled."""
        try:
            hpas, _ = self.client.list("horizontalpodautoscalers")
        except Exception:
            return 0
        scaled = 0
        for hpa in hpas:
            try:
                if self._reconcile(hpa):
                    scaled += 1
            except Exception:
                # one broken HPA (bad scaleRef, metrics source raising,
                # transport error) must not kill the reconcile thread
                continue
        return scaled

    # scaleRef kind -> registry resource (horizontal.go scales through
    # the extensions Scale subresource, never the full object)
    _SCALE_KINDS = {"ReplicationController": "replicationcontrollers",
                    "Deployment": "deployments"}

    def _reconcile(self, hpa: api.HorizontalPodAutoscaler) -> bool:
        ref = hpa.spec.scale_ref
        ns = ref.namespace or hpa.metadata.namespace
        resource = self._SCALE_KINDS.get(ref.kind)
        if resource is None:
            return False
        # read and write through the scale subresource, the reference's
        # contract (horizontal.go reconcileAutoscaler: scales.Get ->
        # compute -> scales.Update; the selector for the metrics query
        # comes from scale.status.selector)
        scale = self.client.get_scale(resource, ref.name, ns)
        current = scale.spec.replicas
        target = hpa.spec.cpu_utilization_target_percentage
        utilization = None
        desired = current
        if target and current > 0:
            utilization = self.metrics(ns, scale.status.selector)
            if utilization is not None:
                ratio = utilization / target
                # inside the tolerance band nothing moves (horizontal.go)
                if abs(ratio - 1.0) > TOLERANCE:
                    desired = int(math.ceil(current * ratio))
        desired = max(hpa.spec.min_replicas,
                      min(hpa.spec.max_replicas, desired))
        desired = self._stabilized(hpa, desired)
        did_scale = desired != current
        if did_scale:
            try:
                self.client.update_scale(
                    resource, ref.name,
                    replace(scale, spec=api.ScaleSpec(replicas=desired)),
                    ns)
            except Exception as e:
                # ref: horizontal.go:145 — a failed rescale records and
                # propagates (the reconcile loop isolates per HPA)
                if self.recorder:
                    self.recorder.eventf(
                        hpa, "Warning", "FailedRescale",
                        "New size: %d; error: %s", desired, e)
                raise
            if self.recorder:
                self.recorder.eventf(hpa, "Normal", "SuccessfulRescale",
                                     "New size: %d", desired)
        self._update_status(hpa, current, desired, utilization, did_scale)
        return did_scale

    def _stabilized(self, hpa: api.HorizontalPodAutoscaler,
                    desired: int) -> int:
        """Damped desired count: the max recommendation over the
        stabilization window. A single-dip recommendation can never
        shrink the fleet; a ramp-down that outlives the window can."""
        if self.downscale_stabilization <= 0:
            return desired
        key = f"{hpa.metadata.namespace}/{hpa.metadata.name}"
        now = self.clock.monotonic()
        floor = now - self.downscale_stabilization
        window = [(ts, d) for ts, d in self._recommendations.get(key, [])
                  if ts >= floor]
        window.append((now, desired))
        self._recommendations[key] = window
        return max(d for _, d in window)

    def _update_status(self, hpa, current, desired, utilization,
                       did_scale) -> None:
        status = api.HorizontalPodAutoscalerStatus(
            observed_generation=hpa.metadata.generation,
            last_scale_time=(api.now_rfc3339() if did_scale
                             else hpa.status.last_scale_time),
            current_replicas=current, desired_replicas=desired,
            current_cpu_utilization_percentage=(
                int(utilization) if utilization is not None else None))
        try:
            self.client.update_status(
                "horizontalpodautoscalers", replace(hpa, status=status),
                hpa.metadata.namespace)
        except (ApiError, NotFound):
            pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.reconcile_once()
            self._stop.wait(self.sync_period)

    def run(self) -> "HorizontalController":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="horizontal-pod-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
