"""Control-plane reconcilers (ref: pkg/controller/*).

Each controller follows the reference pattern: informer caches feed a
deduplicating work queue, worker threads sync one key at a time, all
state re-derivable from the API (crash-only)."""

from .framework import (ControllerExpectations, QueueWorkers,
                        active_pods_sort_key, filter_active_pods)
from .replication import ReplicationManager
from .node import NodeController
from .endpoint import EndpointsController
from .gc import PodGCController
from .namespace import NamespaceController
from .resourcequota import ResourceQuotaController
from .persistentvolume import PersistentVolumeClaimBinder
from .job import JobController
from .daemon import DaemonSetController
from .deployment import DeploymentController
from .podautoscaler import HorizontalController
from .serviceaccount import ServiceAccountsController, TokensController
from .service import RouteController, ServiceController
from .manager import ControllerManager

__all__ = [
    "ControllerExpectations", "QueueWorkers", "active_pods_sort_key",
    "filter_active_pods", "ReplicationManager", "NodeController",
    "EndpointsController", "PodGCController", "NamespaceController",
    "ResourceQuotaController", "PersistentVolumeClaimBinder",
    "JobController", "DaemonSetController", "DeploymentController",
    "HorizontalController", "ServiceAccountsController",
    "TokensController", "ServiceController", "RouteController",
    "ControllerManager",
]
