"""Namespace controller: cascade delete + finalization.

Reference: pkg/controller/namespace/namespace_controller.go — a namespace
whose deletionTimestamp is set has phase Terminating; syncNamespace
(:95-120) deletes every namespaced resource inside it (deleteAllContent
:163-230), then clears the "kubernetes" finalizer (finalizeNamespaceFunc
:128-150); storage drops the namespace once no finalizers remain (that
last step lives in our registry.finalize_namespace)."""

from __future__ import annotations

from dataclasses import replace

from ..api.cache import Informer
from ..api.registry import RESOURCES
from ..core import types as api
from ..core.errors import NotFound
from .framework import QueueWorkers

# content is removed in the reference's fixed order (deleteAllContent);
# bindings is virtual (no storage), events go last like the reference
_CONTENT_RESOURCES = [
    # workload owners before their products (deployments create RCs,
    # jobs/daemonsets/RCs create pods), then the rest, events last
    "deployments", "horizontalpodautoscalers", "jobs", "daemonsets",
    "replicationcontrollers", "pods", "podtemplates", "serviceaccounts",
    "services", "ingresses", "persistentvolumeclaims", "secrets",
    "limitranges", "resourcequotas", "thirdpartyresources", "endpoints",
    "events",
]


class NamespaceController:
    def __init__(self, client, workers: int = 2):
        self.client = client
        self.workers = QueueWorkers(self._sync, workers, name="namespace")
        self.informer = Informer(
            client, "namespaces",
            on_add=self._enqueue,
            on_update=lambda old, new: self._enqueue(new))

    def _enqueue(self, ns: api.Namespace) -> None:
        if ns.metadata.deletion_timestamp is not None:
            self.workers.enqueue(ns.metadata.name)

    def _delete_all_content(self, name: str) -> None:
        """Raises on any failure so the sync is retried rather than
        finalizing a namespace that still has content (the reference
        aborts syncNamespace on deleteAllContent error)."""
        for resource in _CONTENT_RESOURCES:
            if resource not in RESOURCES:
                continue
            items, _ = self.client.list(resource, name)
            for obj in items:
                try:
                    self.client.delete(resource, obj.metadata.name, name)
                except NotFound:
                    pass

    def _sync(self, name: str) -> None:
        try:
            ns = self.client.get("namespaces", name)
        except NotFound:
            return
        if ns.metadata.deletion_timestamp is None:
            return
        if ns.status.phase != "Terminating":
            # registry normally stamps this; belt-and-braces for objects
            # marked by other paths (syncNamespace :101-106)
            try:
                self.client.update_status(
                    "namespaces",
                    replace(ns, status=replace(ns.status,
                                               phase="Terminating")))
            except Exception:
                pass
        self._delete_all_content(name)  # raises -> QueueWorkers retries
        finalized = replace(ns, spec=replace(
            ns.spec, finalizers=[f for f in ns.spec.finalizers
                                 if f != "kubernetes"]))
        try:
            self.client.finalize_namespace(finalized)
        except NotFound:
            pass  # already gone, life is good (finalizeNamespaceFunc :145)

    def run(self) -> "NamespaceController":
        self.informer.start()
        self.workers.start()
        return self

    def stop(self) -> None:
        self.workers.stop()
        self.informer.stop()
