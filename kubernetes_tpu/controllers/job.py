"""Job controller.

Reference: pkg/controller/job/controller.go — syncJob: count active/
succeeded/failed pods by phase, run up to `parallelism` active pods until
`succeeded >= completions`, then mark the Complete condition and delete
leftover active pods. Defaulting follows the reference's api defaults:
parallelism nil -> 1; completions nil -> "any single success completes"
(treated as 1 for the done-check but parallelism still bounds actives).

Failure backoff: replacements for FAILED pods are requeued under a
capped, jittered exponential backoff (escalating while the failure
count keeps growing) instead of recreated on every sync — a
crash-looping Job wave in the trace replay would otherwise turn the
controller into a create-storm against the apiserver. The later
reference grows this as the Job BackoffLimit/failure backoff
(job_controller.go); v1.1 recreates immediately. Blocked requeues are
counted by `job_backoff_requeues_total`.
"""

from __future__ import annotations

import random
import threading
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

from ..api.cache import Informer, meta_namespace_key
from ..core import types as api
from ..core.labels import selector_from_set
from ..utils.clock import Clock, RealClock
from ..utils.metrics import global_metrics
from .framework import (ControllerExpectations, QueueWorkers,
                        active_pods_sort_key)


class JobController:
    def __init__(self, client, workers: int = 5, recorder=None,
                 failure_backoff_initial: float = 0.1,
                 failure_backoff_cap: float = 10.0,
                 clock: Optional[Clock] = None):
        self.client = client
        self.recorder = recorder
        self.failure_backoff_initial = failure_backoff_initial
        self.failure_backoff_cap = failure_backoff_cap
        self.clock = clock or RealClock()
        # key -> (failed count last seen, current delay, not-before)
        self._backoff: Dict[str, Tuple[int, float, float]] = {}
        # keys with a wakeup timer already armed (at most one per key,
        # or a crash-looping wave would breed timers on every sync)
        self._backoff_armed: set = set()
        self._backoff_lock = threading.Lock()
        self.expectations = ControllerExpectations()
        self.workers = QueueWorkers(self._sync, workers, name="job-controller")
        # resync re-drives every job periodically: the controller is
        # otherwise edge-triggered, and a failed status write after the
        # last pod went terminal would leave the job un-Completed
        # forever (no further pod event arrives to re-drive the sync —
        # the trace replay under 5% API faults shook this out)
        self.job_informer = Informer(
            client, "jobs",
            on_add=self._enqueue,
            on_update=lambda old, new: self._enqueue(new),
            on_delete=self._enqueue,
            resync_period=5.0)
        self.pod_informer = Informer(
            client, "pods",
            on_add=self._pod_event(adds=True),
            on_update=lambda old, new: self._enqueue_pod_job(new),
            on_delete=self._pod_event(adds=False))

    def _enqueue(self, job: api.Job) -> None:
        self.workers.enqueue(meta_namespace_key(job))

    def _job_for_pod(self, pod: api.Pod):
        for job in self.job_informer.cache.list():
            if job.metadata.namespace != pod.metadata.namespace:
                continue
            if job.spec.selector and selector_from_set(
                    job.spec.selector).matches(pod.metadata.labels):
                return job
        return None

    def _enqueue_pod_job(self, pod: api.Pod) -> None:
        job = self._job_for_pod(pod)
        if job is not None:
            self._enqueue(job)

    def _pod_event(self, adds: bool):
        def handler(pod: api.Pod) -> None:
            job = self._job_for_pod(pod)
            if job is None:
                return
            key = meta_namespace_key(job)
            if adds:
                self.expectations.creation_observed(key)
            else:
                self.expectations.deletion_observed(key)
            self._enqueue(job)
        return handler

    # ----------------------------------------------------------- sync

    def _job_pods(self, job: api.Job) -> List[api.Pod]:
        sel = selector_from_set(job.spec.selector)
        return [p for p in self.pod_informer.cache.list()
                if p.metadata.namespace == job.metadata.namespace
                and sel.matches(p.metadata.labels)]

    def _sync(self, key: str) -> None:
        job = self.job_informer.cache.get_by_key(key)
        if job is None:
            self.expectations.delete(key)
            with self._backoff_lock:
                self._backoff.pop(key, None)
            return
        pods = self._job_pods(job)
        active = [p for p in pods
                  if p.status.phase in (api.POD_PENDING, api.POD_RUNNING,
                                        api.POD_UNKNOWN, "")
                  and p.metadata.deletion_timestamp is None]
        succeeded = sum(1 for p in pods
                        if p.status.phase == api.POD_SUCCEEDED)
        failed = sum(1 for p in pods if p.status.phase == api.POD_FAILED)

        parallelism = job.spec.parallelism if job.spec.parallelism is not None else 1
        completions = job.spec.completions
        done = (succeeded >= completions if completions is not None
                else succeeded > 0)

        if self.expectations.satisfied(key):
            if done:
                # job finished: tear down still-active pods (controller.go
                # syncJob completion path)
                if active:
                    self.expectations.expect_deletions(key, len(active))
                    for pod in active:
                        self._delete_pod(job, key, pod)
                active = []
            else:
                remaining = (completions - succeeded
                             if completions is not None else parallelism)
                want_active = min(parallelism, remaining)
                diff = want_active - len(active)
                if diff > 0 and self._failure_backoff_active(key, failed):
                    diff = 0  # cooling down; the timer re-drives us
                if diff > 0:
                    self.expectations.expect_creations(key, diff)
                    threads = [threading.Thread(
                        target=self._create_pod, args=(job, key),
                        daemon=True) for _ in range(diff)]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                elif diff < 0:
                    # delete-preference order (controller.ActivePods:
                    # unscheduled < scheduled, Pending < Running,
                    # not-ready < ready) so scale-down discards pods
                    # that have done the least work — the same sort the
                    # RC manager applies (manageJob sorts by ActivePods
                    # before deleting, job/controller.go)
                    active = sorted(active, key=active_pods_sort_key)
                    self.expectations.expect_deletions(key, -diff)
                    for pod in active[:(-diff)]:
                        self._delete_pod(job, key, pod)
                    active = active[(-diff):]

        self._update_status(job, len(active), succeeded, failed, done)

    def _failure_backoff_active(self, key: str, failed: int) -> bool:
        """True while replacements for failed pods must wait. Escalates
        (doubles, capped) each time the failure count grows; a job with
        no failed pods pays nothing. Blocked syncs arm a timer so the
        key re-drives itself when the window expires."""
        now = self.clock.monotonic()
        with self._backoff_lock:
            if failed <= 0:
                self._backoff.pop(key, None)
                return False
            seen, delay, not_before = self._backoff.get(
                key, (0, 0.0, 0.0))
            if failed > seen:
                delay = (self.failure_backoff_initial if delay <= 0
                         else min(delay * 2, self.failure_backoff_cap))
                # full jitter on the top quarter: a wave of jobs
                # failing together must not retry in one synchronized
                # spike (the retry-policy lesson, api/retry.py)
                not_before = now + delay * (0.75 + random.random() * 0.25)
                self._backoff[key] = (failed, delay, not_before)
            remaining = not_before - now
            if remaining <= 0:
                return False
            if key in self._backoff_armed:
                return True  # the armed timer will re-drive this key
            self._backoff_armed.add(key)
        global_metrics.inc("job_backoff_requeues_total",
                           {"job": key})

        def fire():
            with self._backoff_lock:
                self._backoff_armed.discard(key)
            self.workers.enqueue(key)

        timer = threading.Timer(remaining, fire)
        timer.daemon = True
        timer.start()
        return True

    def _create_pod(self, job: api.Job, key: str) -> None:
        tpl = job.spec.template
        pod = api.Pod(
            metadata=api.ObjectMeta(
                generate_name=f"{job.metadata.name}-",
                namespace=job.metadata.namespace,
                labels=dict(tpl.metadata.labels),
                annotations={"kubernetes.io/created-by":
                             f"Job/{job.metadata.name}"}),
            spec=tpl.spec,
            status=api.PodStatus(phase="Pending"))
        try:
            self.client.create("pods", pod, job.metadata.namespace)
            if self.recorder:
                self.recorder.eventf(job, "Normal", "SuccessfulCreate",
                                     "Created pod")
        except Exception:
            self.expectations.creation_observed(key)
            if self.recorder:
                self.recorder.eventf(job, "Warning", "FailedCreate",
                                     "Error creating pod")

    def _delete_pod(self, job: api.Job, key: str, pod: api.Pod) -> None:
        try:
            self.client.delete("pods", pod.metadata.name,
                               pod.metadata.namespace)
            if self.recorder:
                self.recorder.eventf(job, "Normal", "SuccessfulDelete",
                                     "Deleted pod %s", pod.metadata.name)
        except Exception:
            self.expectations.deletion_observed(key)
            if self.recorder:
                self.recorder.eventf(job, "Warning", "FailedDelete",
                                     "Error deleting pod %s",
                                     pod.metadata.name)

    def _update_status(self, job: api.Job, active: int, succeeded: int,
                       failed: int, done: bool) -> None:
        conditions = list(job.status.conditions)
        complete_already = any(c.type == "Complete" and c.status == "True"
                               for c in conditions)
        changed = (job.status.active != active
                   or job.status.succeeded != succeeded
                   or job.status.failed != failed
                   or (done and not complete_already))
        if not changed:
            return
        if done and not complete_already:
            conditions.append(api.JobCondition(type="Complete",
                                               status="True"))
        status = api.JobStatus(
            conditions=conditions,
            start_time=job.status.start_time or api.now_rfc3339(),
            completion_time=(job.status.completion_time
                             or (api.now_rfc3339() if done else None)),
            active=active, succeeded=succeeded, failed=failed)
        try:
            self.client.update_status(
                "jobs", replace(job, status=status), job.metadata.namespace)
        except Exception:
            pass  # next sync retries

    def run(self) -> "JobController":
        self.job_informer.start()
        self.pod_informer.start()
        self.workers.start()
        return self

    def stop(self) -> None:
        self.workers.stop()
        self.job_informer.stop()
        self.pod_informer.stop()
