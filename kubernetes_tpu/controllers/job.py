"""Job controller.

Reference: pkg/controller/job/controller.go — syncJob: count active/
succeeded/failed pods by phase, run up to `parallelism` active pods until
`succeeded >= completions`, then mark the Complete condition and delete
leftover active pods. Defaulting follows the reference's api defaults:
parallelism nil -> 1; completions nil -> "any single success completes"
(treated as 1 for the done-check but parallelism still bounds actives).
"""

from __future__ import annotations

import threading
from dataclasses import replace
from typing import List

from ..api.cache import Informer, meta_namespace_key
from ..core import types as api
from ..core.labels import selector_from_set
from .framework import (ControllerExpectations, QueueWorkers,
                        active_pods_sort_key)


class JobController:
    def __init__(self, client, workers: int = 5, recorder=None):
        self.client = client
        self.recorder = recorder
        self.expectations = ControllerExpectations()
        self.workers = QueueWorkers(self._sync, workers, name="job-controller")
        self.job_informer = Informer(
            client, "jobs",
            on_add=self._enqueue,
            on_update=lambda old, new: self._enqueue(new),
            on_delete=self._enqueue)
        self.pod_informer = Informer(
            client, "pods",
            on_add=self._pod_event(adds=True),
            on_update=lambda old, new: self._enqueue_pod_job(new),
            on_delete=self._pod_event(adds=False))

    def _enqueue(self, job: api.Job) -> None:
        self.workers.enqueue(meta_namespace_key(job))

    def _job_for_pod(self, pod: api.Pod):
        for job in self.job_informer.cache.list():
            if job.metadata.namespace != pod.metadata.namespace:
                continue
            if job.spec.selector and selector_from_set(
                    job.spec.selector).matches(pod.metadata.labels):
                return job
        return None

    def _enqueue_pod_job(self, pod: api.Pod) -> None:
        job = self._job_for_pod(pod)
        if job is not None:
            self._enqueue(job)

    def _pod_event(self, adds: bool):
        def handler(pod: api.Pod) -> None:
            job = self._job_for_pod(pod)
            if job is None:
                return
            key = meta_namespace_key(job)
            if adds:
                self.expectations.creation_observed(key)
            else:
                self.expectations.deletion_observed(key)
            self._enqueue(job)
        return handler

    # ----------------------------------------------------------- sync

    def _job_pods(self, job: api.Job) -> List[api.Pod]:
        sel = selector_from_set(job.spec.selector)
        return [p for p in self.pod_informer.cache.list()
                if p.metadata.namespace == job.metadata.namespace
                and sel.matches(p.metadata.labels)]

    def _sync(self, key: str) -> None:
        job = self.job_informer.cache.get_by_key(key)
        if job is None:
            self.expectations.delete(key)
            return
        pods = self._job_pods(job)
        active = [p for p in pods
                  if p.status.phase in (api.POD_PENDING, api.POD_RUNNING,
                                        api.POD_UNKNOWN, "")
                  and p.metadata.deletion_timestamp is None]
        succeeded = sum(1 for p in pods
                        if p.status.phase == api.POD_SUCCEEDED)
        failed = sum(1 for p in pods if p.status.phase == api.POD_FAILED)

        parallelism = job.spec.parallelism if job.spec.parallelism is not None else 1
        completions = job.spec.completions
        done = (succeeded >= completions if completions is not None
                else succeeded > 0)

        if self.expectations.satisfied(key):
            if done:
                # job finished: tear down still-active pods (controller.go
                # syncJob completion path)
                if active:
                    self.expectations.expect_deletions(key, len(active))
                    for pod in active:
                        self._delete_pod(job, key, pod)
                active = []
            else:
                remaining = (completions - succeeded
                             if completions is not None else parallelism)
                want_active = min(parallelism, remaining)
                diff = want_active - len(active)
                if diff > 0:
                    self.expectations.expect_creations(key, diff)
                    threads = [threading.Thread(
                        target=self._create_pod, args=(job, key),
                        daemon=True) for _ in range(diff)]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                elif diff < 0:
                    # delete-preference order (controller.ActivePods:
                    # unscheduled < scheduled, Pending < Running,
                    # not-ready < ready) so scale-down discards pods
                    # that have done the least work — the same sort the
                    # RC manager applies (manageJob sorts by ActivePods
                    # before deleting, job/controller.go)
                    active = sorted(active, key=active_pods_sort_key)
                    self.expectations.expect_deletions(key, -diff)
                    for pod in active[:(-diff)]:
                        self._delete_pod(job, key, pod)
                    active = active[(-diff):]

        self._update_status(job, len(active), succeeded, failed, done)

    def _create_pod(self, job: api.Job, key: str) -> None:
        tpl = job.spec.template
        pod = api.Pod(
            metadata=api.ObjectMeta(
                generate_name=f"{job.metadata.name}-",
                namespace=job.metadata.namespace,
                labels=dict(tpl.metadata.labels),
                annotations={"kubernetes.io/created-by":
                             f"Job/{job.metadata.name}"}),
            spec=tpl.spec,
            status=api.PodStatus(phase="Pending"))
        try:
            self.client.create("pods", pod, job.metadata.namespace)
            if self.recorder:
                self.recorder.eventf(job, "Normal", "SuccessfulCreate",
                                     "Created pod")
        except Exception:
            self.expectations.creation_observed(key)
            if self.recorder:
                self.recorder.eventf(job, "Warning", "FailedCreate",
                                     "Error creating pod")

    def _delete_pod(self, job: api.Job, key: str, pod: api.Pod) -> None:
        try:
            self.client.delete("pods", pod.metadata.name,
                               pod.metadata.namespace)
            if self.recorder:
                self.recorder.eventf(job, "Normal", "SuccessfulDelete",
                                     "Deleted pod %s", pod.metadata.name)
        except Exception:
            self.expectations.deletion_observed(key)
            if self.recorder:
                self.recorder.eventf(job, "Warning", "FailedDelete",
                                     "Error deleting pod %s",
                                     pod.metadata.name)

    def _update_status(self, job: api.Job, active: int, succeeded: int,
                       failed: int, done: bool) -> None:
        conditions = list(job.status.conditions)
        complete_already = any(c.type == "Complete" and c.status == "True"
                               for c in conditions)
        changed = (job.status.active != active
                   or job.status.succeeded != succeeded
                   or job.status.failed != failed
                   or (done and not complete_already))
        if not changed:
            return
        if done and not complete_already:
            conditions.append(api.JobCondition(type="Complete",
                                               status="True"))
        status = api.JobStatus(
            conditions=conditions,
            start_time=job.status.start_time or api.now_rfc3339(),
            completion_time=(job.status.completion_time
                             or (api.now_rfc3339() if done else None)),
            active=active, succeeded=succeeded, failed=failed)
        try:
            self.client.update_status(
                "jobs", replace(job, status=status), job.metadata.namespace)
        except Exception:
            pass  # next sync retries

    def run(self) -> "JobController":
        self.job_informer.start()
        self.pod_informer.start()
        self.workers.start()
        return self

    def stop(self) -> None:
        self.workers.stop()
        self.job_informer.stop()
        self.pod_informer.stop()
