"""DaemonSet controller.

Reference: pkg/controller/daemon/controller.go — per daemon set: every
schedulable, ready node should run exactly one pod from the template
(pods are created pre-bound via spec.nodeName, bypassing the scheduler,
which is how the reference's daemon controller places them); extra or
misscheduled pods are deleted.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Dict, List

from ..api.cache import Informer, meta_namespace_key
from ..core import types as api
from ..core.labels import selector_from_set
from .framework import ControllerExpectations, QueueWorkers, filter_active_pods


def node_should_run_daemon_pod(node: api.Node,
                               ds: "api.DaemonSet | None" = None) -> bool:
    """Schedulable + Ready (the scheduler's node filter applied here
    because daemon pods never pass through it) + the template's
    nodeSelector against the node's labels (ref:
    pkg/controller/daemon/controller.go:534-535 — also what makes the
    DaemonSetReaper's unmatchable-selector drain work)."""
    if node.spec.unschedulable:
        return False
    for cond in node.status.conditions:
        if cond.type == api.NODE_READY and cond.status != api.CONDITION_TRUE:
            return False
    if ds is not None:
        sel = ds.spec.template.spec.node_selector
        if sel and not selector_from_set(sel).matches(node.metadata.labels):
            return False
    return True


class DaemonSetController:
    def __init__(self, client, workers: int = 5):
        self.client = client
        self.expectations = ControllerExpectations()
        self.workers = QueueWorkers(self._sync, workers, name="daemon-sets")
        self.ds_informer = Informer(
            client, "daemonsets",
            on_add=self._enqueue,
            on_update=lambda old, new: self._enqueue(new),
            on_delete=self._enqueue)
        self.pod_informer = Informer(
            client, "pods",
            on_add=self._pod_event(adds=True),
            on_update=lambda old, new: self._enqueue_pod_ds(new),
            on_delete=self._pod_event(adds=False))
        self.node_informer = Informer(
            client, "nodes",
            on_add=lambda n: self._enqueue_all(),
            on_update=lambda old, new: self._enqueue_all(),
            on_delete=lambda n: self._enqueue_all())

    def _enqueue(self, ds: api.DaemonSet) -> None:
        self.workers.enqueue(meta_namespace_key(ds))

    def _enqueue_all(self) -> None:
        for ds in self.ds_informer.cache.list():
            self._enqueue(ds)

    def _ds_for_pod(self, pod: api.Pod):
        for ds in self.ds_informer.cache.list():
            if ds.metadata.namespace != pod.metadata.namespace:
                continue
            if ds.spec.selector and selector_from_set(
                    ds.spec.selector).matches(pod.metadata.labels):
                return ds
        return None

    def _enqueue_pod_ds(self, pod: api.Pod) -> None:
        ds = self._ds_for_pod(pod)
        if ds is not None:
            self._enqueue(ds)

    def _pod_event(self, adds: bool):
        def handler(pod: api.Pod) -> None:
            ds = self._ds_for_pod(pod)
            if ds is None:
                return
            key = meta_namespace_key(ds)
            if adds:
                self.expectations.creation_observed(key)
            else:
                self.expectations.deletion_observed(key)
            self._enqueue(ds)
        return handler

    # ----------------------------------------------------------- sync

    def _sync(self, key: str) -> None:
        ds = self.ds_informer.cache.get_by_key(key)
        if ds is None:
            self.expectations.delete(key)
            return
        sel = selector_from_set(ds.spec.selector)
        by_node: Dict[str, List[api.Pod]] = {}
        for pod in self.pod_informer.cache.list():
            if pod.metadata.namespace != ds.metadata.namespace:
                continue
            if not sel.matches(pod.metadata.labels):
                continue
            by_node.setdefault(pod.spec.node_name, []).append(pod)

        nodes = self.node_informer.cache.list()
        eligible = {n.metadata.name for n in nodes
                    if node_should_run_daemon_pod(n, ds)}

        to_create: List[str] = []
        to_delete: List[api.Pod] = []
        for node_name in eligible:
            running = filter_active_pods(by_node.get(node_name, []))
            if not running:
                to_create.append(node_name)
            else:
                # one daemon pod per node; extras die oldest-last
                running.sort(key=lambda p: (p.metadata.creation_timestamp,
                                            p.metadata.name))
                to_delete.extend(running[1:])
        for node_name, pods in by_node.items():
            if node_name not in eligible:
                to_delete.extend(filter_active_pods(pods))

        if self.expectations.satisfied(key):
            if to_create:
                self.expectations.expect_creations(key, len(to_create))
                for node_name in to_create:
                    self._create_pod(ds, key, node_name)
            if to_delete:
                self.expectations.expect_deletions(key, len(to_delete))
                for pod in to_delete:
                    self._delete_pod(key, pod)

        scheduled = sum(1 for node_name, pods in by_node.items()
                        if node_name in eligible
                        and filter_active_pods(pods))
        misscheduled = sum(len(filter_active_pods(pods))
                           for node_name, pods in by_node.items()
                           if node_name not in eligible)
        self._update_status(ds, scheduled, misscheduled, len(eligible))

    def _create_pod(self, ds: api.DaemonSet, key: str,
                    node_name: str) -> None:
        tpl = ds.spec.template
        pod = api.Pod(
            metadata=api.ObjectMeta(
                generate_name=f"{ds.metadata.name}-",
                namespace=ds.metadata.namespace,
                labels=dict(tpl.metadata.labels),
                annotations={"kubernetes.io/created-by":
                             f"DaemonSet/{ds.metadata.name}"}),
            spec=replace(tpl.spec, node_name=node_name),
            status=api.PodStatus(phase="Pending"))
        try:
            self.client.create("pods", pod, ds.metadata.namespace)
        except Exception:
            self.expectations.creation_observed(key)

    def _delete_pod(self, key: str, pod: api.Pod) -> None:
        try:
            self.client.delete("pods", pod.metadata.name,
                               pod.metadata.namespace)
        except Exception:
            self.expectations.deletion_observed(key)

    def _update_status(self, ds: api.DaemonSet, scheduled: int,
                       misscheduled: int, desired: int) -> None:
        if (ds.status.current_number_scheduled == scheduled
                and ds.status.number_misscheduled == misscheduled
                and ds.status.desired_number_scheduled == desired):
            return
        try:
            self.client.update_status("daemonsets", replace(
                ds, status=api.DaemonSetStatus(
                    current_number_scheduled=scheduled,
                    number_misscheduled=misscheduled,
                    desired_number_scheduled=desired)),
                ds.metadata.namespace)
        except Exception:
            pass

    def run(self) -> "DaemonSetController":
        self.ds_informer.start()
        self.pod_informer.start()
        self.node_informer.start()
        self.workers.start()
        return self

    def stop(self) -> None:
        self.workers.stop()
        self.ds_informer.stop()
        self.pod_informer.stop()
        self.node_informer.stop()
