"""Service (cloud load balancer) + route controllers.

Reference: pkg/controller/servicecontroller.go — LoadBalancer-type
services get a cloud LB spanning the cluster's nodes; deletes tear it
down — and pkg/controller/routecontroller.go — one cloud route per node
toward its pod CIDR. Both program the cloudprovider interface.
"""

from __future__ import annotations

import threading
from dataclasses import replace
from typing import Dict, List, Optional

from ..cloudprovider import CloudProvider, Route
from ..core import types as api

SYNC_PERIOD = 10.0

# LB names derive from the service UID (the reference's cloudprovider
# naming, e.g. GCE's "a<uid>"): "a" + first 12 uid chars
_LB_NAME_LEN = 13


def _lb_name(svc: api.Service) -> str:
    if svc.metadata.uid:
        return f"a{svc.metadata.uid[:12]}"
    return f"a{svc.metadata.namespace}-{svc.metadata.name}"[:_LB_NAME_LEN]


def _is_owned_lb_name(name: str) -> bool:
    return len(name) == _LB_NAME_LEN and name.startswith("a")


class ServiceController:
    def __init__(self, client, cloud: CloudProvider,
                 sync_period: float = SYNC_PERIOD, recorder=None):
        self.client = client
        self.cloud = cloud
        self.recorder = recorder
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # lb name -> the requested address already attempted once (the
        # recreate-on-mismatch path fires a single time per value)
        self._ip_attempts: Dict[str, str] = {}

    def sync_once(self) -> int:
        balancers = self.cloud.load_balancers()
        zones = self.cloud.zones()
        if balancers is None:
            return 0
        region = zones.get_zone().region if zones else ""
        try:
            services, _ = self.client.list("services")
            nodes, _ = self.client.list("nodes")
        except Exception:
            return 0
        hosts = sorted(n.metadata.name for n in nodes)
        actions = 0
        wanted = set()
        for svc in services:
            lb_name = _lb_name(svc)
            if svc.spec.type != "LoadBalancer":
                if svc.status.load_balancer_ingress:
                    # downgraded from LoadBalancer: the GC below removes
                    # the cloud LB; the stale external IP must go too
                    try:
                        self.client.update_status("services", replace(
                            svc, status=api.ServiceStatus()),
                            svc.metadata.namespace)
                        actions += 1
                    except Exception:
                        pass
                continue
            wanted.add(lb_name)
            # one broken service (bad loadBalancerIP, provider error)
            # must not kill reconciliation for every other service —
            # the reference's controller records the error per service
            # and keeps going (servicecontroller.go processDelta)
            try:
                lb = balancers.get(lb_name, region)
                # order-insensitive: providers report ports sorted (ELB
                # listeners and GCE rules have no spec order to
                # preserve)
                ports = sorted(p.port for p in svc.spec.ports)
                want_ip = svc.spec.load_balancer_ip
                if want_ip and not getattr(
                        balancers, "supports_load_balancer_ip", True):
                    # capability check BEFORE any mutation (aws.go
                    # rejects a requested publicIP up front): never
                    # tear down a working LB chasing an address the
                    # provider cannot grant
                    if self.recorder:
                        self.recorder.eventf(
                            svc, "Warning", "LoadBalancerIPUnsupported",
                            "provider cannot honor loadBalancerIP %s; "
                            "keeping the provider-assigned address",
                            want_ip)
                    want_ip = ""
                if (lb is not None and want_ip
                        and lb.external_ip != want_ip
                        and self._ip_attempts.get(lb_name) != want_ip):
                    # the requested address is honored at creation only
                    # (forwarding rules/vips are address-immutable):
                    # recreate ONCE per requested value, like gce.go's
                    # forwardingRuleNeedsUpdate IPAddress check — a
                    # provider that grants a different address anyway
                    # must not trigger delete/recreate churn every sync
                    balancers.delete(lb_name, region)
                    lb = None
                if lb is None or sorted(lb.ports) != ports \
                        or lb.hosts != hosts:
                    lb = balancers.ensure(
                        lb_name, region, ports, hosts,
                        load_balancer_ip=want_ip)
                    actions += 1
                if want_ip:
                    self._ip_attempts[lb_name] = want_ip
                    if lb.external_ip != want_ip and self.recorder:
                        self.recorder.eventf(
                            svc, "Warning", "LoadBalancerIPNotGranted",
                            "requested %s, provider granted %s",
                            want_ip, lb.external_ip)
            except Exception as e:
                if self.recorder:
                    self.recorder.eventf(
                        svc, "Warning", "CreatingLoadBalancerFailed",
                        "Error creating load balancer: %s", e)
                continue
            ingress = [lb.external_ip]
            if svc.status.load_balancer_ingress != ingress:
                try:
                    self.client.update_status("services", replace(
                        svc, status=api.ServiceStatus(
                            load_balancer_ingress=ingress)),
                        svc.metadata.namespace)
                except Exception:
                    pass
        # prune one-shot recreate suppressions for balancers outside the
        # wanted set: a deleted-then-recreated service mints a new uid
        # (new lb name), but a same-name recreate under a provider that
        # reuses uids — or a service flapping LoadBalancer<->ClusterIP —
        # must get its one recreate attempt back instead of inheriting
        # the dead entry forever (the map also stops leaking an entry
        # per deleted service)
        for name in [n for n in self._ip_attempts if n not in wanted]:
            del self._ip_attempts[name]
        # tear down balancers whose service is gone or downgraded — via
        # the interface's list(), and ONLY balancers carrying this
        # controller's naming convention: LBs we never created (operators,
        # other clusters on the same provider) are not ours to delete
        try:
            existing = balancers.list()
        except NotImplementedError:
            existing = []
        for lb in existing:
            if lb.name not in wanted and _is_owned_lb_name(lb.name):
                balancers.delete(lb.name, lb.region)
                actions += 1
        return actions

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:
                pass  # transient provider failure: next period retries
            self._stop.wait(self.sync_period)

    def run(self) -> "ServiceController":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="service-controller")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


class RouteController:
    """(ref: routecontroller.go — reconcile node routes)"""

    def __init__(self, client, cloud: CloudProvider,
                 cluster_cidr: str = "10.244.0.0/16",
                 sync_period: float = SYNC_PERIOD):
        self.client = client
        self.cloud = cloud
        self.cluster_cidr = cluster_cidr
        self.sync_period = sync_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _in_cluster_cidr(self, cidr: str) -> bool:
        import ipaddress
        try:
            return ipaddress.ip_network(cidr).subnet_of(
                ipaddress.ip_network(self.cluster_cidr))
        except ValueError:
            return False

    def sync_once(self) -> int:
        routes = self.cloud.routes()
        if routes is None:
            return 0
        try:
            nodes, _ = self.client.list("nodes")
        except Exception:
            return 0
        # reconcile by TARGET INSTANCE, not route name
        # (routecontroller.go:73 routeMap[route.TargetInstance]): the
        # route's cloud-side name is provider-internal — EC2 routes
        # have none at all (identity = destination CIDR), GCE names
        # are mangled — so node association is the only portable key
        existing = routes.list_routes()
        by_target = {r.target_instance: r for r in existing}
        node_cidrs = {}
        refreshed = set()  # targets re-created THIS pass: their stale
        #                    entry in `existing` must not be GC'd again
        actions = 0
        for node in nodes:
            if not node.spec.pod_cidr:
                # no CIDR assigned yet: nothing to route (the reference
                # waits for the node controller's CIDR allocation)
                continue
            name = node.metadata.name
            cidr = node.spec.pod_cidr
            node_cidrs[name] = cidr
            route = by_target.get(name)
            if route is None or route.destination_cidr != cidr:
                if route is not None:
                    # CIDR reassigned: drop the stale route first — the
                    # Routes contract doesn't promise overwrite
                    routes.delete_route(route.name)
                routes.create_route(Route(
                    name=f"route-{name}", target_instance=name,
                    destination_cidr=cidr))
                refreshed.add(name)
                actions += 1
        for route in existing:
            if route.target_instance in refreshed:
                continue
            # only GC routes INSIDE the cluster CIDR — operator routes
            # are not ours to delete (routecontroller.go
            # isResponsibleForRoute)
            if node_cidrs.get(route.target_instance) != \
                    route.destination_cidr and \
                    self._in_cluster_cidr(route.destination_cidr):
                routes.delete_route(route.name)
                actions += 1
        return actions

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sync_once()
            except Exception:
                pass  # transient provider failure: next period retries
            self._stop.wait(self.sync_period)

    def run(self) -> "RouteController":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="route-controller")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
