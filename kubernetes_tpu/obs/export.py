"""Span-dump analysis and Chrome/Perfetto trace-event export.

The export target is the trace-event JSON format both chrome://tracing
and ui.perfetto.dev open directly — the same viewer that reads the
jax-profiler's XPlane dumps, so a scheduling trace and a device
profile sit side by side. Everything here is a pure function of the
span dicts (Span.to_dict shape): no clock reads, no RNG, no ambient
state — determinism of the export reduces to determinism of the spans.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..utils.metrics import OBS_STAGES

#: trace-event "thread" rows: one per lifecycle stage plus a catch-all
#: track 1 for unstaged spans — fixed ids, so the export never depends
#: on real thread identity (which no two runs share)
_UNSTAGED_TID = 1
_STAGE_TID = {stage: i + 2 for i, stage in enumerate(OBS_STAGES)}


def _tid(stage: Optional[str]) -> int:
    return _STAGE_TID.get(stage or "", _UNSTAGED_TID)


def to_trace_events(spans: List[dict]) -> List[dict]:
    """Span dicts -> trace-event dicts ("X" complete events on stage
    tracks, preceded by "M" thread-name metadata). Stable sort by
    (ts, trace_id, span_id): concurrent spans order by identity, not
    by buffer arrival, so same-seed runs serialize identically."""
    out: List[dict] = [
        {"ph": "M", "pid": 1, "tid": _UNSTAGED_TID,
         "name": "thread_name", "args": {"name": "spans"}}]
    for stage in OBS_STAGES:
        out.append({"ph": "M", "pid": 1, "tid": _STAGE_TID[stage],
                    "name": "thread_name", "args": {"name": stage}})
    events = []
    for s in spans:
        if s.get("end") is None:
            continue
        args = {"trace_id": s["trace_id"], "span_id": s["span_id"],
                "parent_id": s["parent_id"], "status": s["status"]}
        for k, v in (s.get("attrs") or {}).items():
            args[str(k)] = v
        steps = s.get("steps") or []
        if steps:
            args["steps"] = [[int(t * 1e6), msg] for t, msg in steps]
        events.append({
            "ph": "X", "pid": 1, "tid": _tid(s.get("stage")),
            "name": s["name"], "cat": s.get("stage") or "span",
            "ts": int(s["start"] * 1e6),
            "dur": int((s["end"] - s["start"]) * 1e6),
            "args": args})
    events.sort(key=lambda e: (e["ts"], e["args"]["trace_id"],
                               e["args"]["span_id"]))
    out.extend(events)
    return out


def stage_totals(spans: List[dict]) -> Dict[str, dict]:
    """-> {stage: {count, total_seconds}} over finished staged spans —
    the numerator of the bench's stage-coverage gate."""
    out: Dict[str, dict] = {}
    for s in spans:
        stage = s.get("stage")
        if stage is None or s.get("end") is None:
            continue
        acc = out.setdefault(stage, {"count": 0, "total_seconds": 0.0})
        acc["count"] += 1
        acc["total_seconds"] += s["end"] - s["start"]
    return out


def critical_path(spans: List[dict], trace_id: str) -> List[dict]:
    """The latest-finisher chain of one trace: from the root span,
    repeatedly descend into the child that ended last — the chain a
    'why was this pod slow' investigation walks. Returns span dicts
    root-first; [] for an unknown trace."""
    members = [s for s in spans
               if s["trace_id"] == trace_id and s.get("end") is not None]
    if not members:
        return []
    by_id = {s["span_id"]: s for s in members}
    children: Dict[str, List[dict]] = {}
    roots = []
    for s in members:
        parent = s["parent_id"]
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    if not roots:
        return []
    path = [max(roots, key=lambda s: (s["end"], s["span_id"]))]
    while True:
        kids = children.get(path[-1]["span_id"])
        if not kids:
            return path
        path.append(max(kids, key=lambda s: (s["end"], s["span_id"])))
