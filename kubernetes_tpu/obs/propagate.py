"""Trace-context propagation codecs.

Two carriers, one context shape:

- the W3C `traceparent` HTTP header (`00-<32hex>-<16hex>-<2hex>`) —
  HttpClient injects one per request ATTEMPT (fresh span id, shared
  trace id, so a retry storm reads as sibling attempts of one trace),
  ApiServer extracts it into the server span;
- the trace.kubernetes.io/traceparent object annotation — stamped at
  create admission, it rides the object through the store, the WAL,
  every watch replay/live delivery and every wire serialization, which
  is how the scheduler's informer links a tile back to the creates
  that fed it without the Event type growing a side channel.
"""

from __future__ import annotations

from typing import Any, Optional

#: object-annotation carrier of the create-time trace context
TRACEPARENT_ANNOTATION = "trace.kubernetes.io/traceparent"

_VERSION = "00"
_FLAGS = "01"  # sampled

_HEX = set("0123456789abcdef")


def format_traceparent(ctx: Any) -> str:
    """ctx: anything with trace_id/span_id (Span or SpanContext)."""
    return f"{_VERSION}-{ctx.trace_id}-{ctx.span_id}-{_FLAGS}"


def parse_traceparent(value: Optional[str]):
    """-> SpanContext, or None for anything malformed (an unparseable
    header starts a fresh trace rather than failing the request —
    the W3C processing model's tolerant-reader posture)."""
    from . import SpanContext
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    if not (set(version) <= _HEX and set(trace_id) <= _HEX
            and set(span_id) <= _HEX):
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id)


def ctx_of(obj: Any):
    """The create-time trace context an API object carries, or None.
    Reads metadata.annotations[TRACEPARENT_ANNOTATION]."""
    meta = getattr(obj, "metadata", None)
    ann = getattr(meta, "annotations", None)
    if not ann:
        return None
    return parse_traceparent(ann.get(TRACEPARENT_ANNOTATION))
