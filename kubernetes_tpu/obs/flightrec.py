"""Flight recorder: one post-mortem bundle per triggering event.

When something goes wrong mid-soak — an SLO burn-rate alert trips, the
runtime lock witness sees an inversion, a chaos plan kills a process —
the most valuable artifacts are the ones that exist RIGHT THEN: the
tail of the fleet time-series, the tracer's span buffer, the witness
graph, and where in its plan the chaos was. By the time the run ends
they are diluted or gone. dump() snapshots all of them into one
directory the way a crashed airliner's recorder is read back:

    <dir>/bundle-0003-slo-crowd-bind-availability/
        meta.json     trigger, sequence number, clock reads, extras
        series.json   fleet time-series tail (FleetScraper.tail)
        trace.json    span dump (obs.Tracer.export_json format)
        witness.json  lock-order graph + inversions (LockWitness.report)
        chaos.json    chaos-plan position (CrashChaos.trace, ...)

Every file is sorted + compact (byte-stable under FakeClock), and
every section is optional — the recorder writes what it was handed.
tools/obs_report.py renders bundles alongside the series report.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Dict, List, Optional

from ..utils.clock import REAL, Clock


def _slug(text: str) -> str:
    return re.sub(r"[^a-zA-Z0-9]+", "-", text).strip("-").lower()[:60]


def _dump_json(path: str, doc: Any) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True, separators=(",", ":"))


class FlightRecorder:
    """Bounded post-mortem bundle writer. `capacity` caps the number
    of bundles per run (a flapping alert must not fill the disk);
    once full, further dumps are counted but dropped."""

    def __init__(self, directory: str, clock: Optional[Clock] = None,
                 capacity: int = 16, series_tail: int = 120):
        self.directory = directory
        self.clock = clock or REAL
        self.capacity = capacity
        self.series_tail = series_tail
        self.dropped = 0
        self._seq = 0
        self._lock = threading.Lock()
        self.bundles: List[str] = []

    def dump(self, reason: str,
             scraper: Any = None,
             tracer: Any = None,
             witness: Any = None,
             chaos: Any = None,
             extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write one bundle; returns its path (None when over
        capacity). Never raises on a partially-available world — a
        recorder that crashes the thing it is recording is worse
        than no recorder."""
        with self._lock:
            if self._seq >= self.capacity:
                self.dropped += 1
                return None
            seq = self._seq
            self._seq += 1
        bundle = os.path.join(self.directory,
                              f"bundle-{seq:04d}-{_slug(reason)}")
        os.makedirs(bundle, exist_ok=True)

        _dump_json(os.path.join(bundle, "meta.json"), {
            "reason": reason,
            "seq": seq,
            "monotonic": self.clock.monotonic(),
            "wall": self.clock.now(),
            "extra": extra or {},
        })
        if scraper is not None:
            try:
                _dump_json(os.path.join(bundle, "series.json"),
                           scraper.tail(self.series_tail))
            except Exception:
                pass
        if tracer is not None:
            try:
                with open(os.path.join(bundle, "trace.json"), "w",
                          encoding="utf-8") as f:
                    f.write(tracer.export_json())
            except Exception:
                pass
        if witness is not None:
            try:
                _dump_json(os.path.join(bundle, "witness.json"),
                           witness.report())
            except Exception:
                pass
        if chaos is not None:
            try:
                pos = (chaos.trace() if hasattr(chaos, "trace")
                       else chaos if isinstance(chaos, dict)
                       else {"repr": repr(chaos)})
                _dump_json(os.path.join(bundle, "chaos.json"), pos)
            except Exception:
                pass
        with self._lock:
            self.bundles.append(bundle)
        return bundle
