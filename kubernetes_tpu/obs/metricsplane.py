"""Fleet metrics plane: deterministic scrape -> merge -> time-series
-> SLO burn rates.

The reference posture is an external Prometheus scraping every
component's /metrics on a wall-clock cadence and an Alertmanager
evaluating burn-rate rules over the TSDB. This port keeps the exact
same pipeline shape — exposition text is really parsed, histograms are
really merged, alerts really trip — but runs it in-process on the
injectable `utils/clock.Clock` with seeded jitter, so a same-seed
`FakeClock` run exports a byte-identical series artifact and alert
trip/clear ticks are part of the replayable contract (DIVERGENCES
#30). Soaks gate on alerts, not just end-of-run values.

Pipeline:
  Target.scrape()      -> Prometheus exposition text (HTTP or in-proc)
  parse_exposition()   -> {family: kind + per-labelset points}
  FleetScraper.sample():
      per-target counter-reset rebase (a crash-restarted process's
      counters restart at 0; rates must never go negative), then
      sum counters / merge histograms across targets into ONE fleet
      sample appended to a bounded ring
  FleetScraper.export_json() -> sorted, byte-stable JSON series
  BurnRateEvaluator.observe(sample) -> deterministic TRIP/CLEAR events

Histograms merge because utils/metrics.py pins per-metric bucket
boundaries; summaries expose only _sum/_count here (a p99 of p99s is
not a p99 — the merged percentile story belongs to histograms).
"""

from __future__ import annotations

import json
import random
import threading
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.clock import REAL, Clock
from ..utils.metrics import (HISTOGRAM_BUCKETS, Histogram, MetricsRegistry,
                             _fmt_labels, _key)

# ------------------------------------------------------------ parsing


def _unescape(val: str) -> str:
    out, i = [], 0
    while i < len(val):
        c = val[i]
        if c == "\\" and i + 1 < len(val):
            nxt = val[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, c + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(body: str) -> Dict[str, str]:
    """`a="x",b="y"` -> dict, honoring \\\\ \\" \\n escapes."""
    labels: Dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        name = body[i:eq].strip().lstrip(",").strip()
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {body[eq:]!r}")
        j = eq + 2
        while j < len(body):
            if body[j] == "\\":
                j += 2
                continue
            if body[j] == '"':
                break
            j += 1
        labels[name] = _unescape(body[eq + 2:j])
        i = j + 1
    return labels


def _parse_sample_line(line: str) -> Tuple[str, Dict[str, str], float]:
    if "{" in line:
        name, rest = line.split("{", 1)
        body, valpart = rest.rsplit("}", 1)
        return name, _parse_labels(body), float(valpart.strip())
    name, valpart = line.split(None, 1)
    return name, {}, float(valpart)


@dataclass
class Family:
    """One metric family from one exposition: kind + points keyed by
    the sorted-labels tuple. Histogram points are de-cumulated back
    into Histogram objects (mergeable); summaries keep only the
    mergeable _sum/_count pair."""

    name: str
    kind: str  # counter | gauge | histogram | summary | untyped
    points: Dict[tuple, float] = field(default_factory=dict)
    hists: Dict[tuple, Histogram] = field(default_factory=dict)
    sums: Dict[tuple, Tuple[float, float]] = field(default_factory=dict)


def parse_exposition(text: str) -> Dict[str, Family]:
    """Parse Prometheus text exposition into families. Round-trips
    MetricsRegistry.render() exactly (the golden test), and accepts
    the subset any of this repo's components serve."""
    kinds: Dict[str, str] = {}
    flat: Dict[str, Dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        name, labels, value = _parse_sample_line(line)
        flat.setdefault(name, {})[_key(labels)] = value

    out: Dict[str, Family] = {}
    for fam_name, kind in kinds.items():
        fam = Family(fam_name, kind)
        if kind == "histogram":
            # regroup _bucket/_sum/_count by base labels, rebuild the
            # per-bucket counts from the cumulative exposition
            buckets: Dict[tuple, List[Tuple[float, float]]] = {}
            for k, v in flat.get(fam_name + "_bucket", {}).items():
                le = dict(k)["le"]
                base = _key({n: x for n, x in k if n != "le"})
                bound = float("inf") if le == "+Inf" else float(le)
                buckets.setdefault(base, []).append((bound, v))
            for base, pairs in buckets.items():
                pairs.sort()
                bounds = tuple(b for b, _ in pairs if b != float("inf"))
                h = Histogram(bounds)
                prev = 0.0
                cum = [c for _, c in pairs]
                for i, c in enumerate(cum):
                    h.counts[i] = int(round(c - prev))
                    prev = c
                h.total = flat.get(fam_name + "_sum", {}).get(base, 0.0)
                h.count = int(flat.get(fam_name + "_count",
                                       {}).get(base, prev))
                fam.hists[base] = h
        elif kind == "summary":
            for k, v in flat.get(fam_name + "_sum", {}).items():
                cnt = flat.get(fam_name + "_count", {}).get(k, 0.0)
                fam.sums[k] = (v, cnt)
        else:
            fam.points = dict(flat.get(fam_name, {}))
        out[fam_name] = fam
    return out


# ------------------------------------------------------------- targets


class RegistryTarget:
    """In-proc component registry (scheduler, controllers, fleet, the
    soak harness itself) — scraped through render(), not object
    access, so the parser path is exercised for every target."""

    def __init__(self, name: str, registry: MetricsRegistry):
        self.name = name
        self._registry = registry

    def scrape(self) -> str:
        return self._registry.render()


class HttpTarget:
    """A /metrics endpoint over the wire (apiserver, kubelet). The
    endpoint is shed-exempt on the apiserver (like /healthz) so this
    keeps reading during a 429/503 storm."""

    def __init__(self, name: str, url: str, timeout_s: float = 5.0):
        self.name = name
        self.url = url
        self.timeout_s = timeout_s

    def scrape(self) -> str:
        with urllib.request.urlopen(self.url,
                                    timeout=self.timeout_s) as resp:
            return resp.read().decode()


class CallableTarget:
    """Escape hatch: any () -> exposition-text callable."""

    def __init__(self, name: str, fn: Callable[[], str]):
        self.name = name
        self._fn = fn

    def scrape(self) -> str:
        return self._fn()


# ------------------------------------------------- reset-aware folding


class _CounterState:
    """Per-(target, metric, labelset) monotone rebase: when a raw
    cumulative value goes DOWN the process behind it restarted, so the
    pre-crash total is banked into `base` and the adjusted value
    (base + raw) stays monotone — a rate over it never goes negative.
    """

    __slots__ = ("last", "base")

    def __init__(self) -> None:
        self.last = 0.0
        self.base = 0.0

    def adjust(self, raw: float) -> Tuple[float, bool]:
        reset = raw < self.last
        if reset:
            self.base += self.last
        self.last = raw
        return self.base + raw, reset


class _HistState:
    """Reset rebase for a histogram point: a restart zeroes counts,
    so bank the pre-crash histogram and merge it under the fresh one.
    Reset signal: the cumulative observation count went down."""

    __slots__ = ("last_count", "banked")

    def __init__(self) -> None:
        self.last_count = 0
        self.banked: Optional[Histogram] = None

    def adjust(self, raw: Histogram,
               prev_raw: Optional[Histogram]) -> Tuple[Histogram, bool]:
        reset = raw.count < self.last_count
        if reset and prev_raw is not None:
            self.banked = (prev_raw if self.banked is None
                           else self.banked.merge(prev_raw))
        self.last_count = raw.count
        return (raw if self.banked is None
                else self.banked.merge(raw)), reset


# ------------------------------------------------------------- scraper


def _lstr(k: tuple) -> str:
    """Canonical label-set key for JSON: the exposition label string
    ('' for the empty set) — already sorted, already escaped."""
    return _fmt_labels(k)


class FleetScraper:
    """Clocked scrape -> fold -> ring. One sample() pulls every
    target, rebases counter resets per target, then folds into one
    fleet view: counters and gauges sum across targets and label
    sets stay separate; histograms with pinned boundaries merge
    exactly. Samples land in a bounded ring; export_json() is sorted
    and byte-stable (same-seed FakeClock runs are byte-identical —
    tier-1 gated, like the tracer's span export).
    """

    def __init__(self, targets: List, clock: Optional[Clock] = None,
                 cadence_s: float = 1.0, jitter_s: float = 0.0,
                 seed: int = 0, capacity: int = 4096):
        self.targets = list(targets)
        self.clock = clock or REAL
        self.cadence_s = cadence_s
        self.jitter_s = jitter_s
        self.seed = seed
        # seeded per-(seed, stream) jitter draw — the scrape analogue
        # of the chaos plans' fixed-draw contract
        self._rng = random.Random(f"{seed}:metricsplane")
        self._ring: List[dict] = []
        self._capacity = capacity
        self._lock = threading.Lock()
        # (target, metric, lstr) -> rebase state
        self._cstate: Dict[tuple, _CounterState] = {}
        self._hstate: Dict[tuple, _HistState] = {}
        self._praw: Dict[tuple, Histogram] = {}
        self.resets_total = 0
        self.errors_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._n = 0

    # ------------------------------------------------------ one round

    def sample(self, t: Optional[float] = None) -> dict:
        """Scrape every target once and append the folded fleet
        sample. `t` defaults to the clock's monotonic read; soaks
        pass their tick index so the time axis is replayable."""
        if t is None:
            t = self.clock.monotonic()
        counters: Dict[str, Dict[str, float]] = {}
        gauges: Dict[str, Dict[str, float]] = {}
        hists: Dict[str, Dict[str, Histogram]] = {}
        resets = 0
        errors = 0
        for target in self.targets:
            try:
                fams = parse_exposition(target.scrape())
            except Exception:
                errors += 1
                continue
            for fam in fams.values():
                if fam.kind == "histogram":
                    for k, h in fam.hists.items():
                        key = (target.name, fam.name, k)
                        st = self._hstate.get(key)
                        if st is None:
                            st = self._hstate[key] = _HistState()
                        adj, was_reset = st.adjust(h, self._praw.get(key))
                        self._praw[key] = h
                        resets += was_reset
                        cur = hists.setdefault(fam.name,
                                               {}).get(_lstr(k))
                        hists[fam.name][_lstr(k)] = \
                            adj if cur is None else cur.merge(adj)
                    continue
                if fam.kind == "summary":
                    # only the mergeable pair survives aggregation
                    for k, (s, c) in fam.sums.items():
                        for suffix, raw in (("_sum", s), ("_count", c)):
                            name = fam.name + suffix
                            key = (target.name, name, k)
                            st = self._cstate.get(key)
                            if st is None:
                                st = self._cstate[key] = _CounterState()
                            adj, was_reset = st.adjust(raw)
                            resets += was_reset
                            d = counters.setdefault(name, {})
                            d[_lstr(k)] = d.get(_lstr(k), 0.0) + adj
                    continue
                sink = gauges if fam.kind == "gauge" else counters
                for k, v in fam.points.items():
                    if fam.kind == "gauge":
                        d = sink.setdefault(fam.name, {})
                        d[_lstr(k)] = d.get(_lstr(k), 0.0) + v
                        continue
                    key = (target.name, fam.name, k)
                    st = self._cstate.get(key)
                    if st is None:
                        st = self._cstate[key] = _CounterState()
                    adj, was_reset = st.adjust(v)
                    resets += was_reset
                    d = counters.setdefault(fam.name, {})
                    d[_lstr(k)] = d.get(_lstr(k), 0.0) + adj
        self.resets_total += resets
        self.errors_total += errors
        smp = {
            "t": t,
            "counters": counters,
            "gauges": gauges,
            "histograms": {
                name: {ls: h.to_dict() for ls, h in by_label.items()}
                for name, by_label in hists.items()},
            "resets": resets,
            "errors": errors,
        }
        with self._lock:
            self._ring.append(smp)
            if len(self._ring) > self._capacity:
                del self._ring[0]
            self._n += 1
        return smp

    # ------------------------------------------------------ the series

    def series(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def tail(self, n: int) -> List[dict]:
        with self._lock:
            return list(self._ring[-n:])

    def export_json(self) -> str:
        """Sorted, compact, byte-stable series artifact — the
        metrics-plane twin of Tracer.export_json()."""
        with self._lock:
            doc = {
                "cadence_s": self.cadence_s,
                "jitter_s": self.jitter_s,
                "seed": self.seed,
                "targets": sorted(t.name for t in self.targets),
                "resets_total": self.resets_total,
                "errors_total": self.errors_total,
                "samples": list(self._ring),
            }
        return json.dumps(doc, sort_keys=True, separators=(",", ":"))

    # --------------------------------------------------- clocked loop

    def start(self) -> "FleetScraper":
        """Background sampler at the fixed cadence plus a seeded
        jitter draw per round (Prometheus jitters scrapes so targets
        don't see a thundering herd; ours is replayable)."""
        def loop() -> None:
            while not self._stop.is_set():
                self.clock.sleep(self.cadence_s
                                 + self._rng.uniform(0.0, self.jitter_s))
                if self._stop.is_set():
                    return
                self.sample()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-scraper")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ----------------------------------------------------- SLO burn rates


@dataclass(frozen=True)
class SLODef:
    """One pinned SLO over the fleet series.

    kind "ratio": good/total are two cumulative counters (summed
    across label sets); error ratio over a window of W samples is
    1 - d(good)/d(total).  kind "histogram_le": good events are the
    observations <= threshold_le of a pinned histogram (the bound
    must be a pinned bucket boundary — exact, no interpolation),
    total is its _count.

    Burn rate = error_ratio / error_budget, error_budget =
    1 - objective. Multi-window alerting per the SRE workbook: the
    alert TRIPs when both the fast and the slow window burn over
    their thresholds (fast alone is noise-prone, slow alone is
    laggy), and CLEARs as soon as the fast window calms.
    """

    name: str
    metric: str                 # total counter, or histogram name
    kind: str = "ratio"         # "ratio" | "histogram_le"
    good_metric: str = ""       # ratio: the good-events counter
    threshold_le: float = 0.0   # histogram_le: pinned bucket bound
    objective: float = 0.999
    fast_window: int = 2        # samples
    slow_window: int = 8
    fast_burn: float = 10.0
    slow_burn: float = 2.0

    @property
    def budget(self) -> float:
        return 1.0 - self.objective


@dataclass(frozen=True)
class AlertEvent:
    """A deterministic alert edge: sample index + both burn rates at
    the edge. Soaks gate on these (trip AND clear), not just final
    values — the alert timeline is part of the replayable contract."""

    sample: int
    t: float
    slo: str
    action: str   # "TRIP" | "CLEAR"
    fast_burn: float
    slow_burn: float

    def to_dict(self) -> dict:
        return {"sample": self.sample, "t": self.t, "slo": self.slo,
                "action": self.action,
                "fast_burn": round(self.fast_burn, 4),
                "slow_burn": round(self.slow_burn, 4)}


def _counter_total(sample: dict, name: str) -> float:
    return sum(sample.get("counters", {}).get(name, {}).values())


def _hist_good_total(sample: dict, name: str,
                     le: float) -> Tuple[float, float]:
    good = total = 0.0
    for d in sample.get("histograms", {}).get(name, {}).values():
        h = Histogram.from_dict(d)
        good += h.quantile_le(le)
        total += h.count
    return good, total


class BurnRateEvaluator:
    """Feed fleet samples in order; collect TRIP/CLEAR events. Pure
    function of the sample stream — two same-seed runs produce the
    same events at the same sample indices."""

    def __init__(self, slos: List[SLODef],
                 on_trip: Optional[Callable[[AlertEvent], None]] = None,
                 on_clear: Optional[Callable[[AlertEvent], None]] = None):
        self.slos = list(slos)
        self.events: List[AlertEvent] = []
        self._on_trip = on_trip
        self._on_clear = on_clear
        # per-slo: cumulative (good, total) per sample + active flag
        self._track: Dict[str, List[Tuple[float, float]]] = \
            {s.name: [] for s in self.slos}
        self._active: Dict[str, bool] = {s.name: False for s in self.slos}
        self._idx = -1

    @staticmethod
    def _good_total(slo: SLODef, sample: dict) -> Tuple[float, float]:
        if slo.kind == "histogram_le":
            return _hist_good_total(sample, slo.metric, slo.threshold_le)
        return (_counter_total(sample, slo.good_metric),
                _counter_total(sample, slo.metric))

    def _burn(self, slo: SLODef, window: int) -> float:
        track = self._track[slo.name]
        hi = track[-1]
        lo = track[max(0, len(track) - 1 - window)]
        d_total = hi[1] - lo[1]
        if d_total <= 0:
            return 0.0
        err = max(0.0, 1.0 - (hi[0] - lo[0]) / d_total)
        return err / slo.budget

    def observe(self, sample: dict) -> List[AlertEvent]:
        """Evaluate one appended sample; returns the events it fired."""
        self._idx += 1
        fired: List[AlertEvent] = []
        for slo in self.slos:
            self._track[slo.name].append(self._good_total(slo, sample))
            fast = self._burn(slo, slo.fast_window)
            slow = self._burn(slo, slo.slow_window)
            active = self._active[slo.name]
            if not active and fast >= slo.fast_burn \
                    and slow >= slo.slow_burn:
                ev = AlertEvent(self._idx, sample.get("t", 0.0),
                                slo.name, "TRIP", fast, slow)
            elif active and fast < slo.fast_burn:
                ev = AlertEvent(self._idx, sample.get("t", 0.0),
                                slo.name, "CLEAR", fast, slow)
            else:
                continue
            self._active[slo.name] = ev.action == "TRIP"
            self.events.append(ev)
            fired.append(ev)
            cb = self._on_trip if ev.action == "TRIP" else self._on_clear
            if cb is not None:
                cb(ev)
        return fired

    def active(self, slo_name: str) -> bool:
        return self._active.get(slo_name, False)

    def events_dict(self) -> List[dict]:
        return [e.to_dict() for e in self.events]


def evaluate_series(slos: List[SLODef],
                    series: List[dict]) -> List[AlertEvent]:
    """Offline replay of the evaluator over a recorded series — what
    tools/obs_report.py runs on an exported artifact."""
    ev = BurnRateEvaluator(slos)
    for sample in series:
        ev.observe(sample)
    return ev.events
