"""Causal pod-lifecycle tracing: the observability layer.

The reference's story here is glog + pprof + per-binary /metrics
(pkg/util/trace.go, hack/grab-profiles.sh); none of it can answer
*where a pod's wall-clock goes* between create and kubelet confirm.
This package is that answer as a layer: W3C-style `traceparent`
propagation through the whole control plane (HttpClient injects,
ApiServer extracts, objects carry it as an annotation through the
store and every watch stream), a span recorder whose IDs are a pure
function of `(seed, counter)` and whose timestamps ride the injectable
utils/clock.Clock — so under the PR-10 determinism contract a
same-seed run exports byte-identical trace-event JSON — and a
pod-lifecycle stage model (utils/metrics.OBS_STAGES) recorded as
`pod_e2e_stage_seconds{stage=...}` summaries.

Propagation model: within a thread, context is an explicit stack
(`use(span)` / `current()`); across queues and processes it travels
with the data — the `traceparent` header on HTTP requests, the
trace.kubernetes.io/traceparent annotation on objects (stamped at
create admission, carried by the store, the WAL, every watch replay
and every wire serialization for free). Tile-granular spans (a 30k-pod
bind commits as one span) adopt the first pod's context as an
exemplar parent and record the pod count, the OpenTelemetry-exemplar
compromise to a span with 30k parents.

Disabled tracing is a few attribute reads per call site: `start_span`
returns a shared no-op span and `end` returns immediately — the
bench's tracing-off arm gates the overhead at <5% e2e throughput.

Sibling modules (imported directly, not re-exported here, to keep
this package's import graph flat): `obs.metricsplane` is the fleet
metrics plane — deterministic scraper, merged pinned-bucket
histograms, SLO burn-rate alerting over the exported time-series —
and `obs.flightrec` is the flight recorder that snapshots series
tail + span buffer + lock-witness graph + chaos position into a
post-mortem bundle the instant something trips.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional

from ..utils.clock import REAL, Clock
from ..utils.metrics import (OBS_STAGE_SUMMARY, OBS_STAGES, MetricsRegistry,
                             global_metrics)
from .export import critical_path, to_trace_events
from .propagate import (TRACEPARENT_ANNOTATION, ctx_of, format_traceparent,
                        parse_traceparent)

__all__ = [
    "Span", "SpanContext", "Tracer", "tracer", "configure", "set_tracer",
    "current", "use", "format_traceparent", "parse_traceparent",
    "TRACEPARENT_ANNOTATION", "ctx_of", "to_trace_events", "critical_path",
    "OBS_STAGES", "OBS_STAGE_SUMMARY", "NOOP",
]


class SpanContext(NamedTuple):
    """The propagated identity of a span: what a traceparent header or
    an object annotation carries."""
    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars


class Span:
    """One timed operation. Mutable until `Tracer.end` seals it; the
    recorder owns the buffer, a Span is just the handle call sites
    hold while the operation runs."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "stage", "status", "attrs", "steps")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str, start: float,
                 stage: Optional[str] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end: Optional[float] = None
        self.stage = stage
        self.status = "ok"
        self.attrs: Dict[str, Any] = attrs or {}
        #: (timestamp, message) step marks — the utils/trace.Trace
        #: over-threshold logging view reads these
        self.steps: List[tuple] = []

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration(self) -> float:
        return (self.end - self.start) if self.end is not None else 0.0

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start": self.start, "end": self.end, "stage": self.stage,
                "status": self.status, "attrs": self.attrs,
                "steps": [list(s) for s in self.steps]}


class _NoopSpan(Span):
    """The disabled-tracer span: one shared instance, every mutation a
    no-op, so call sites never branch on enablement themselves."""

    def __init__(self):
        super().__init__("noop", "0" * 32, "0" * 16, "", 0.0)


NOOP = _NoopSpan()


class Tracer:
    """Deterministic span recorder.

    IDs: span n of a tracer is sha256(f"{seed}:{n}") — trace_id is the
    first 16 bytes, span_id the next 8 — the same (seed, stream-name)
    string-seeding convention chaos.FaultPlan uses, with no RNG at all
    (the determinism lint bans process RNG in this package).
    Timestamps: every read goes through the injected Clock's monotonic
    axis, so a FakeClock harness replays traces bit-for-bit.

    The buffer is a bounded deque (oldest spans fall off); `end`
    additionally feeds stage-tagged spans into the
    pod_e2e_stage_seconds{stage=...} summary of the metrics registry.
    """

    def __init__(self, seed: int = 0, clock: Optional[Clock] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 enabled: bool = True, capacity: int = 200_000):
        self.seed = seed
        self.clock = clock or REAL
        self.metrics = metrics or global_metrics
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counter = 0
        self._spans: deque = deque(maxlen=capacity)

    # ------------------------------------------------------------- ids

    def _next_ids(self) -> tuple:
        with self._lock:
            n = self._counter
            self._counter += 1
        h = hashlib.sha256(f"{self.seed}:{n}".encode()).hexdigest()
        return h[:32], h[32:48]

    # ----------------------------------------------------------- record

    def start_span(self, name: str, parent: Any = None,
                   stage: Optional[str] = None,
                   attrs: Optional[Dict[str, Any]] = None,
                   start: Optional[float] = None) -> Span:
        """parent: a Span, a SpanContext, or None (starts a new trace).
        start: explicit monotonic timestamp (defaults to a clock read)."""
        if not self.enabled:
            return NOOP
        trace_id, span_id = self._next_ids()
        parent_id = ""
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        return Span(name, trace_id, span_id, parent_id,
                    self.clock.monotonic() if start is None else start,
                    stage=stage, attrs=attrs)

    def end(self, span: Span, status: str = "ok",
            end: Optional[float] = None) -> None:
        if span is NOOP or not self.enabled:
            return
        span.end = self.clock.monotonic() if end is None else end
        span.status = status
        with self._lock:
            self._spans.append(span)
        if span.stage is not None:
            self.metrics.observe(OBS_STAGE_SUMMARY, span.end - span.start,
                                 {"stage": span.stage})

    def span(self, name: str, parent: Any = None,
             stage: Optional[str] = None,
             attrs: Optional[Dict[str, Any]] = None):
        """Context manager: start_span / end with error status on
        exception, and the span installed as the current context."""
        return _SpanScope(self, name, parent, stage, attrs)

    def record(self, name: str, start: float, end: float,
               parent: Any = None, stage: Optional[str] = None,
               attrs: Optional[Dict[str, Any]] = None) -> Span:
        """Record an already-timed interval (call sites that measured
        with their own clock reads — the scheduler's tile timings)."""
        if not self.enabled:
            return NOOP
        s = self.start_span(name, parent=parent, stage=stage, attrs=attrs,
                            start=start)
        self.end(s, end=end)
        return s

    def step(self, span: Span, msg: str) -> None:
        if span is NOOP or not self.enabled:
            return
        span.steps.append((self.clock.monotonic(), msg))

    # ------------------------------------------------------------- read

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def reset(self) -> None:
        """Drop recorded spans AND rewind the id counter — two runs
        separated by reset() draw identical id sequences."""
        with self._lock:
            self._spans.clear()
            self._counter = 0

    def trace_events(self) -> List[dict]:
        return to_trace_events([s.to_dict() for s in self.spans()])

    def export_json(self) -> str:
        """Deterministic Chrome/Perfetto trace-event JSON: stable sort,
        sorted keys, no whitespace — the byte-identical same-seed
        contract the soak gate asserts."""
        return json.dumps(self.trace_events(), sort_keys=True,
                          separators=(",", ":"))


class _SpanScope:
    def __init__(self, tracer: Tracer, name: str, parent: Any,
                 stage: Optional[str], attrs: Optional[dict]):
        self._tracer = tracer
        self._args = (name, parent, stage, attrs)
        self.span: Span = NOOP

    def __enter__(self) -> Span:
        name, parent, stage, attrs = self._args
        if parent is None:
            parent = current()
        self.span = self._tracer.start_span(name, parent=parent,
                                            stage=stage, attrs=attrs)
        _push(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        _pop(self.span)
        self._tracer.end(self.span,
                         status="error" if exc_type is not None else "ok")


# -------------------------------------------------- thread-local context

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _push(span: Span) -> None:
    if span is not NOOP:
        _stack().append(span)


def _pop(span: Span) -> None:
    st = _stack()
    if span is not NOOP and st and st[-1] is span:
        st.pop()


def current() -> Optional[SpanContext]:
    """The active span context on THIS thread (explicit-stack model:
    queues and processes carry context with the data, not the thread)."""
    st = getattr(_tls, "stack", None)
    if not st:
        return None
    return st[-1].context


class use:
    """Install a span (or bare SpanContext) as the current context for
    a block — the apiserver wraps routing in one so registry/store
    spans nest under the server span."""

    def __init__(self, span_or_ctx: Any):
        if isinstance(span_or_ctx, SpanContext):
            # promote to a Span-shaped holder for the stack
            span = Span("ctx", span_or_ctx.trace_id, span_or_ctx.span_id,
                        "", 0.0)
        else:
            span = span_or_ctx
        self._span = span

    def __enter__(self):
        _push(self._span)
        return self._span

    def __exit__(self, *exc) -> None:
        _pop(self._span)


# --------------------------------------------------------- global tracer

#: the process-wide tracer, like utils.metrics.global_metrics: every
#: layer records into it unless handed its own. Replace with
#: configure() (harnesses) or set_tracer() (tests restoring in finally).
_global_tracer = Tracer()


def tracer() -> Tracer:
    return _global_tracer


def configure(seed: int = 0, clock: Optional[Clock] = None,
              metrics: Optional[MetricsRegistry] = None,
              enabled: bool = True, capacity: int = 200_000) -> Tracer:
    """Replace the global tracer (bench/soak harnesses pin seed+clock
    here before driving traffic). Returns the new tracer."""
    global _global_tracer
    _global_tracer = Tracer(seed=seed, clock=clock, metrics=metrics,
                            enabled=enabled, capacity=capacity)
    return _global_tracer


def set_tracer(t: Tracer) -> Tracer:
    """Swap the global tracer, returning the previous one (tests)."""
    global _global_tracer
    prev = _global_tracer
    _global_tracer = t
    return prev
