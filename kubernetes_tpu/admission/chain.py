"""Ordered admission chain (ref: pkg/admission/chain.go)."""

from __future__ import annotations

from typing import List

from .interfaces import Attributes, Interface


class Chain(Interface):
    def __init__(self, plugins: List[Interface]):
        self.plugins = list(plugins)

    def admit(self, attributes: Attributes) -> None:
        for plugin in self.plugins:
            if not plugin.handles(attributes.operation):
                continue
            plugin.admit(attributes)

    def handles(self, operation: str) -> bool:
        return any(p.handles(operation) for p in self.plugins)
