"""Admission control: the ordered mutating/validating plugin chain every
write passes through before hitting storage.

Reference: pkg/admission (interfaces.go, chain.go, plugins.go) and the
plugin set under plugin/pkg/admission (admit, deny, limitranger,
namespace autoprovision/exists/lifecycle, resourcequota, serviceaccount,
securitycontext). Wired into the registry's write path (the reference
wires it into the apiserver handlers, resthandler.go:326 createHandler ->
admit.Admit; our registry IS the handler layer both HTTP and in-proc
clients share).
"""

from .interfaces import Attributes, Forbidden, Interface, Operation
from .chain import Chain
from .plugins import new_from_plugins, register_plugin


def registry_hook(chain: Chain):
    """Adapt a Chain to the Registry.admission callable. Usage:

        registry = Registry()
        registry.admission = registry_hook(
            new_from_plugins(registry, ["NamespaceLifecycle", ...]))
    """
    def hook(operation, resource, obj, namespace="", name=""):
        attrs = Attributes(object=obj, namespace=namespace, name=name,
                           resource=resource, operation=operation)
        chain.admit(attrs)
        return attrs.object
    return hook


__all__ = ["Attributes", "Forbidden", "Interface", "Operation", "Chain",
           "new_from_plugins", "register_plugin", "registry_hook"]
