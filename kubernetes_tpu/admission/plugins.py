"""Admission plugins (ref: plugin/pkg/admission/*).

Each factory takes the Registry (the plugins' view of cluster state — the
reference wires a client + informers; in-proc the registry is both) and
returns an Interface. Register order matters: the chain runs in the order
names are given to new_from_plugins, mirroring --admission-control's
comma-ordered list (cmd/kube-apiserver/app/server.go:230).
"""

from __future__ import annotations

from typing import Callable, Dict, List

from ..core import types as api
from ..core.errors import BadRequest, NotFound
from ..core.quantity import Quantity
from .chain import Chain
from .interfaces import Attributes, Forbidden, Interface, Operation


_factories: Dict[str, Callable] = {}


def register_plugin(name: str, factory: Callable) -> str:
    _factories[name] = factory
    return name


def new_from_plugins(registry, names: List[str]) -> Chain:
    """(ref: pkg/admission/plugins.go NewFromPlugins)"""
    plugins: List[Interface] = []
    for name in names:
        if name not in _factories:
            raise BadRequest(f"unknown admission plugin {name!r}")
        plugins.append(_factories[name](registry))
    return Chain(plugins)


class AlwaysAdmit(Interface):
    """(ref: plugin/pkg/admission/admit)"""

    def admit(self, attributes: Attributes) -> None:
        return None


class AlwaysDeny(Interface):
    """(ref: plugin/pkg/admission/deny)"""

    def admit(self, attributes: Attributes) -> None:
        raise Forbidden("this action is not permitted (AlwaysDeny)")


class NamespaceLifecycle(Interface):
    """Block creates into missing or Terminating namespaces; block deleting
    the protected system namespaces (ref:
    plugin/pkg/admission/namespace/lifecycle)."""

    immortal = ("default",)

    def __init__(self, registry):
        self.registry = registry

    def admit(self, attributes: Attributes) -> None:
        if attributes.resource == "namespaces" and \
                attributes.operation == Operation.DELETE:
            if attributes.name in self.immortal:
                raise Forbidden(
                    f"namespace {attributes.name!r} cannot be deleted")
            return
        if attributes.operation != Operation.CREATE:
            return
        if not attributes.namespace or attributes.resource in ("namespaces",
                                                               "events"):
            # events stay recordable during teardown (dedup/TTL storage
            # makes them harmless; blocking them hides the teardown story)
            return
        try:
            ns = self.registry.get("namespaces", attributes.namespace)
        except NotFound:
            raise Forbidden(
                f"namespace {attributes.namespace!r} does not exist")
        if ns.status.phase == "Terminating":
            raise Forbidden(
                f"namespace {attributes.namespace!r} is terminating")


class NamespaceExists(Interface):
    """(ref: plugin/pkg/admission/namespace/exists)"""

    def __init__(self, registry):
        self.registry = registry

    def admit(self, attributes: Attributes) -> None:
        if not attributes.namespace or attributes.resource == "namespaces":
            return
        try:
            self.registry.get("namespaces", attributes.namespace)
        except NotFound:
            raise Forbidden(
                f"namespace {attributes.namespace!r} does not exist")


class NamespaceAutoProvision(Interface):
    """(ref: plugin/pkg/admission/namespace/autoprovision)"""

    def __init__(self, registry):
        self.registry = registry

    def admit(self, attributes: Attributes) -> None:
        if attributes.operation != Operation.CREATE:
            return
        if not attributes.namespace or attributes.resource == "namespaces":
            return
        try:
            self.registry.get("namespaces", attributes.namespace)
        except NotFound:
            try:
                self.registry.create("namespaces", api.Namespace(
                    metadata=api.ObjectMeta(name=attributes.namespace)))
            except Exception:
                pass  # raced with another provisioner: fine either way


class LimitRanger(Interface):
    """Apply LimitRange defaults and enforce min/max on pod containers
    (ref: plugin/pkg/admission/limitranger; container-type limits)."""

    def __init__(self, registry):
        self.registry = registry

    def handles(self, operation: str) -> bool:
        return operation in (Operation.CREATE, Operation.UPDATE)

    def admit(self, attributes: Attributes) -> None:
        if attributes.resource != "pods" or attributes.object is None:
            return
        ranges, _ = self.registry.list("limitranges", attributes.namespace)
        if not ranges:
            return
        pod: api.Pod = attributes.object
        for lr in ranges:
            for item in lr.spec.limits:
                if item.type and item.type != "Container":
                    continue
                for c in pod.spec.containers:
                    self._apply(c, item, pod.metadata.name)

    @staticmethod
    def _apply(container: api.Container, item, pod_name: str) -> None:
        requests = dict(container.resources.requests)
        for resource, default in item.default.items():
            requests.setdefault(resource, default)
        for resource, lo in item.min.items():
            got = requests.get(resource)
            if got is not None and got.milli < lo.milli:
                raise Forbidden(
                    f"pod {pod_name!r}: {resource} request {got} below "
                    f"minimum {lo}")
        for resource, hi in item.max.items():
            got = requests.get(resource)
            if got is not None and got.milli > hi.milli:
                raise Forbidden(
                    f"pod {pod_name!r}: {resource} request {got} above "
                    f"maximum {hi}")
        container.resources.requests = requests


def pod_usage(pod: api.Pod) -> Dict[str, int]:
    """Quota usage of one pod in Quantity milli units — the single
    formula shared by the admission increment and the quota controller's
    recalculation (controllers/resourcequota.py); keep them identical or
    the two paths drift."""
    cpu = 0
    mem = 0
    for c in pod.spec.containers:
        req = c.resources.requests
        if "cpu" in req:
            cpu += req["cpu"].milli
        if "memory" in req:
            mem += req["memory"].milli
    return {"pods": 1000, "cpu": cpu, "memory": mem}


class ResourceQuota(Interface):
    """Enforce ResourceQuota hard limits, incrementing status.used on
    admission (ref: plugin/pkg/admission/resourcequota). Decrements happen
    via controllers.resourcequota.ResourceQuotaController's periodic
    recalculation, like the reference's resourcequota controller resync —
    run it alongside this plugin or deletes never free quota."""

    COUNTED = {"pods": "pods", "services": "services",
               "replicationcontrollers": "replicationcontrollers",
               "secrets": "secrets", "resourcequotas": "resourcequotas"}

    def __init__(self, registry):
        self.registry = registry

    def handles(self, operation: str) -> bool:
        return operation == Operation.CREATE

    def admit(self, attributes: Attributes) -> None:
        count_key = self.COUNTED.get(attributes.resource)
        if count_key is None:
            return
        quotas, _ = self.registry.list("resourcequotas", attributes.namespace)
        for quota in quotas:
            self._charge(quota, attributes, count_key)

    def _charge(self, quota, attributes: Attributes, count_key: str) -> None:
        deltas: Dict[str, int] = {}
        hard = quota.spec.hard
        if count_key in hard:
            deltas[count_key] = 1000  # whole-unit Quantity milli
        if attributes.resource == "pods" and attributes.object is not None:
            usage = pod_usage(attributes.object)
            for resource in ("cpu", "memory"):
                if resource in hard:
                    deltas[resource] = usage[resource]
        if not deltas:
            return

        def apply(cur):
            used = dict(cur.status.used)
            for resource, delta in deltas.items():
                if resource not in cur.spec.hard:
                    # a concurrent writer dropped this resource from
                    # spec.hard; nothing to enforce or charge for it
                    continue
                limit = cur.spec.hard[resource].milli
                have = used.get(resource, Quantity(0)).milli
                if have + delta > limit:
                    raise Forbidden(
                        f"exceeded quota {cur.metadata.name!r}: "
                        f"{resource} used {have}m + {delta}m > hard "
                        f"{limit}m")
                used[resource] = Quantity(have + delta)
            from dataclasses import replace
            return replace(cur, status=api.ResourceQuotaStatus(
                hard=dict(cur.spec.hard), used=used))

        # CAS loop through the store (GuaranteedUpdate semantics,
        # etcd_helper.go:449): a concurrent admit retries, so two pods
        # can't both squeeze under the same last slot
        self.registry.guaranteed_update(
            "resourcequotas", quota.metadata.name, attributes.namespace,
            apply)


class ServiceAccountPlugin(Interface):
    """Default and validate pod service accounts, and mount the
    account's API token secret into every container
    (ref: plugin/pkg/admission/serviceaccount/admission.go:88,150,339;
    DefaultAPITokenMountPath :48)."""

    TOKEN_MOUNT_PATH = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, registry):
        self.registry = registry

    def handles(self, operation: str) -> bool:
        return operation == Operation.CREATE

    def admit(self, attributes: Attributes) -> None:
        if attributes.resource != "pods" or attributes.object is None:
            return
        pod: api.Pod = attributes.object
        if not pod.spec.service_account_name:
            pod.spec.service_account_name = "default"
        try:
            sa = self.registry.get("serviceaccounts",
                                   pod.spec.service_account_name,
                                   attributes.namespace)
        except NotFound:
            raise Forbidden(
                f"service account {attributes.namespace}/"
                f"{pod.spec.service_account_name} does not exist")
        self._mount_token(sa, pod)

    def _referenced_token(self, sa: api.ServiceAccount) -> str:
        """First referenced secret that exists AND is a
        service-account-token typed secret for this account
        (admission.go getReferencedServiceAccountToken /
        serviceaccount.IsServiceAccountToken) — a stray non-token
        reference must not get mounted at the credentials path."""
        for ref in sa.secrets:
            if not ref.name:
                continue
            try:
                secret = self.registry.get("secrets", ref.name,
                                           sa.metadata.namespace)
            except NotFound:
                continue
            if (secret.type == "kubernetes.io/service-account-token"
                    and secret.metadata.annotations.get(
                        "kubernetes.io/service-account.name")
                    == sa.metadata.name):
                return ref.name
        return ""

    def _mount_token(self, sa: api.ServiceAccount, pod: api.Pod) -> None:
        """(admission.go:339 mountServiceAccountToken) The first
        referenced token secret becomes a read-only secret volume
        mounted at the well-known path in every container that doesn't
        already mount something there. No token yet (the tokens
        controller hasn't caught up) -> admit without one, like the
        reference's MountServiceAccountToken w/o RequireAPIToken."""
        token = self._referenced_token(sa)
        if not token:
            return
        vol_name = ""
        names = set()
        for v in pod.spec.volumes:
            names.add(v.name)
            if v.secret is not None and v.secret.secret_name == token:
                vol_name = v.name
        if not vol_name:
            vol_name = token
            n = 0
            while vol_name in names:  # uniquify (SimpleNameGenerator)
                n += 1
                vol_name = f"{token}-{n}"
        mounted_any = False
        for c in pod.spec.containers:
            if any(m.mount_path == self.TOKEN_MOUNT_PATH
                   for m in c.volume_mounts):
                continue  # an existing mount at the path wins
            c.volume_mounts.append(api.VolumeMount(
                name=vol_name, mount_path=self.TOKEN_MOUNT_PATH,
                read_only=True))
            mounted_any = True
        if mounted_any and vol_name not in names:
            pod.spec.volumes.append(api.Volume(
                name=vol_name,
                secret=api.SecretVolumeSource(secret_name=token)))


class SecurityContextDeny(Interface):
    """Deny privilege escalation requests (ref:
    plugin/pkg/admission/securitycontext/scdeny, adapted to this schema:
    privileged containers and host-network pods)."""

    def __init__(self, registry):
        self.registry = registry

    def handles(self, operation: str) -> bool:
        return operation in (Operation.CREATE, Operation.UPDATE)

    def admit(self, attributes: Attributes) -> None:
        if attributes.resource != "pods" or attributes.object is None:
            return
        pod: api.Pod = attributes.object
        if pod.spec.host_network:
            raise Forbidden("pod.spec.hostNetwork is forbidden")
        if pod.spec.host_pid:
            raise Forbidden("pod.spec.hostPID is forbidden")
        if pod.spec.host_ipc:
            raise Forbidden("pod.spec.hostIPC is forbidden")
        from ..kubelet.securitycontext import effective_privileged
        for c in pod.spec.containers:
            sc = getattr(c, "security_context", None)
            # same flat-or-nested resolution the runtime grants by —
            # admission and enforcement must police one predicate
            if effective_privileged(c):
                raise Forbidden(
                    f"privileged container {c.name!r} is forbidden")
            # the reference's scdeny also rejects user/capability
            # requests (plugin/pkg/admission/securitycontext/scdeny:
            # SecurityContext.RunAsUser / SELinuxOptions are denied)
            if sc is not None and (sc.run_as_user is not None
                                   or sc.capabilities is not None):
                raise Forbidden(
                    f"container {c.name!r}: security context "
                    f"user/capability requests are forbidden")


# the InitialResources usage history: image -> {"cpu"|"memory": milli}.
# Process-global by design — it plays the reference's shared metrics DB
# (influxdb/GCM), not per-apiserver state.
usage_history: Dict[str, Dict[str, int]] = {}


def record_usage(image: str, resource: str, milli: int) -> None:
    """Feed the InitialResources history (the kubelet-stats role)."""
    usage_history.setdefault(image, {})[resource] = int(milli)


class DenyExecOnPrivileged(Interface):
    """Reject exec into pods that run privileged or host-network
    (ref: plugin/pkg/admission/exec/denyprivileged — intercepts the
    pods/exec CONNECT; our apiserver relay consults it before relaying
    to the kubelet)."""

    def __init__(self, registry):
        self.registry = registry

    def handles(self, operation: str) -> bool:
        return operation == Operation.CONNECT

    def admit(self, attributes: Attributes) -> None:
        if attributes.resource != "pods/exec":
            return
        try:
            pod = self.registry.get("pods", attributes.name,
                                    attributes.namespace)
        except NotFound:
            return  # missing pod fails later with a clean 404
        # any other lookup failure propagates: a security admission
        # plugin must fail CLOSED, not open
        from ..kubelet.securitycontext import effective_privileged
        if (pod.spec.host_network or pod.spec.host_pid or pod.spec.host_ipc
                or any(effective_privileged(c)
                       for c in pod.spec.containers)):
            # ref: plugin/pkg/admission/exec/admission.go:93-97 — the
            # deny-escalating-exec plugin blocks hostPID and hostIPC
            # pods alongside privileged and host-network ones
            raise Forbidden(
                f"cannot exec into privileged/host-namespace pod "
                f"{attributes.name!r}")


class InitialResources(Interface):
    """Fill absent container CPU/memory requests from observed usage
    (ref: plugin/pkg/admission/initialresources — the reference queries
    an influxdb/GCM history, a store shared by every consumer; the
    analogue here is the module-level `usage_history`, fed via
    `record_usage` by whatever meters containers, or a custom
    `estimator(image, resource) -> milli or None`)."""

    def __init__(self, registry, estimator=None):
        self.registry = registry
        self.estimator = estimator or (
            lambda image, resource:
            usage_history.get(image, {}).get(resource))

    def handles(self, operation: str) -> bool:
        return operation == Operation.CREATE

    def admit(self, attributes: Attributes) -> None:
        if attributes.resource != "pods" or attributes.object is None:
            return
        pod: api.Pod = attributes.object
        for c in pod.spec.containers:
            for resource in ("cpu", "memory"):
                if resource in c.resources.requests:
                    continue
                milli = self.estimator(c.image, resource)
                if milli is not None:
                    c.resources.requests[resource] = Quantity(int(milli))


register_plugin("AlwaysAdmit", lambda r: AlwaysAdmit())
register_plugin("AlwaysDeny", lambda r: AlwaysDeny())
register_plugin("NamespaceLifecycle", NamespaceLifecycle)
register_plugin("NamespaceExists", NamespaceExists)
register_plugin("NamespaceAutoProvision", NamespaceAutoProvision)
register_plugin("LimitRanger", LimitRanger)
register_plugin("ResourceQuota", ResourceQuota)
register_plugin("ServiceAccount", ServiceAccountPlugin)
register_plugin("SecurityContextDeny", SecurityContextDeny)
register_plugin("DenyExecOnPrivileged", DenyExecOnPrivileged)
register_plugin("InitialResources", InitialResources)
