"""Admission interfaces (ref: pkg/admission/interfaces.go)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..core.errors import Forbidden  # noqa: F401 (re-exported: the
# admission rejection error, ref admission.NewForbidden -> 403)


class Operation:
    CREATE = "CREATE"
    UPDATE = "UPDATE"
    DELETE = "DELETE"
    CONNECT = "CONNECT"  # exec/attach/proxy subresources


@dataclass
class Attributes:
    """(ref: interfaces.go Attributes)"""
    object: Any = None
    namespace: str = ""
    name: str = ""
    resource: str = ""
    operation: str = Operation.CREATE
    user_name: str = ""


class Interface:
    """One admission plugin. admit() may MUTATE attributes.object (the
    mutating plugins: limitranger defaults, serviceaccount injection) or
    raise Forbidden/ApiError to reject the request."""

    def admit(self, attributes: Attributes) -> None:
        raise NotImplementedError

    def handles(self, operation: str) -> bool:
        return True
