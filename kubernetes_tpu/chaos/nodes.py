"""Seeded node-fault injection: kill / heartbeat-freeze / flap plans
driving a HollowFleet.

Determinism contract — the same fixed-draw discipline as `FaultPlan`
(injector.py): every fault class owns an independent RNG stream seeded
from `(plan.seed, purpose)`, and victim selection is ONE `sample` draw
over the SORTED node-name list, so the set of nodes a plan kills,
freezes or flaps is a pure function of (seed, node names, fraction) —
independent of thread interleaving, registration order, or how many
times other streams were consumed. `schedule(names)` replays what any
live run with this seed MUST have drawn; `NodeChaos.trace()` returns
what a run actually did, and the node-kill soak gates on the two being
equal (tests/test_chaos.py).

The flap schedule's TIMING is wall-clock (a background toggler), like
every other latency in the harness; the determinism contract covers
victim selection, not toggle phase.

Reference: the reference grows this as test/e2e/chaosmonkey's node
killer (ChaosMonkey + e2e framework's RestartNodes); v1.1 has no
equivalent — see DIVERGENCES.md.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional


@dataclass
class NodeFaultPlan:
    """One seed, one reproducible node-fault schedule."""

    seed: int = 0
    #: fraction of the fleet hard-killed (heartbeats + pod confirms stop)
    kill_fraction: float = 0.0
    #: fraction whose heartbeats freeze (partition sim; kubelet alive)
    freeze_fraction: float = 0.0
    #: fraction that flaps Ready<->NotReady while flapping runs
    flap_fraction: float = 0.0
    #: seconds between flap toggles
    flap_period: float = 0.5

    def stream(self, purpose: str) -> random.Random:
        # str seeding hashes via sha512 — stable across processes
        # (same rule as FaultPlan.stream)
        return random.Random(f"{self.seed}:node:{purpose}")

    def _pick(self, purpose: str, names: Iterable[str],
              fraction: float) -> List[str]:
        """Deterministic victims: one sample draw over sorted names."""
        pool = sorted(names)
        k = int(len(pool) * fraction)
        if k <= 0:
            return []
        return sorted(self.stream(purpose).sample(pool, k))

    def kill_set(self, names: Iterable[str]) -> List[str]:
        return self._pick("kill", names, self.kill_fraction)

    def freeze_set(self, names: Iterable[str]) -> List[str]:
        return self._pick("freeze", names, self.freeze_fraction)

    def flap_set(self, names: Iterable[str]) -> List[str]:
        return self._pick("flap", names, self.flap_fraction)

    def schedule(self, names: Iterable[str]) -> Dict[str, List[str]]:
        """What a live run with this seed MUST select — the pure replay
        the reproducibility gate compares a trace against."""
        names = list(names)
        return {"kill": self.kill_set(names),
                "freeze": self.freeze_set(names),
                "flap": self.flap_set(names)}


class NodeChaos:
    """Drive a HollowFleet from a NodeFaultPlan, recording a trace."""

    def __init__(self, fleet, plan: NodeFaultPlan):
        self.fleet = fleet
        self.plan = plan
        self._trace: Dict[str, List[str]] = {"kill": [], "freeze": [],
                                             "flap": []}
        self._flap_stop = threading.Event()
        self._flap_thread: Optional[threading.Thread] = None

    def trace(self) -> Dict[str, List[str]]:
        """Victim sets actually applied — a run is reproducible when
        this equals plan.schedule(fleet.node_names()) for every fault
        class the run triggered."""
        return {k: list(v) for k, v in self._trace.items()}

    def kill(self) -> List[str]:
        """Hard-kill the plan's kill set; returns the victims."""
        victims = self.plan.kill_set(self.fleet.node_names())
        self._trace["kill"] = self.fleet.kill_nodes(victims)
        return self._trace["kill"]

    def freeze(self) -> List[str]:
        """Freeze the plan's freeze set's heartbeats (partition sim)."""
        victims = self.plan.freeze_set(self.fleet.node_names())
        self.fleet.freeze_heartbeats(victims)
        self._trace["freeze"] = victims
        return victims

    def thaw(self) -> None:
        """End the partition: frozen heartbeats resume."""
        self.fleet.thaw_heartbeats(self._trace["freeze"])

    def start_flapping(self) -> List[str]:
        """Background toggler: the plan's flap set bounces
        Ready<->NotReady every flap_period (heartbeats keep flowing —
        the controller sees honest, rapid condition flips)."""
        victims = self.plan.flap_set(self.fleet.node_names())
        self._trace["flap"] = victims
        if not victims:
            return victims

        def toggle():
            down = False
            while not self._flap_stop.wait(self.plan.flap_period):
                down = not down
                self.fleet.set_not_ready(victims, down)

        self._flap_thread = threading.Thread(target=toggle, daemon=True,
                                             name="node-chaos-flap")
        self._flap_thread.start()
        return victims

    def stop_flapping(self) -> None:
        self._flap_stop.set()
        if self._flap_thread is not None:
            self._flap_thread.join(timeout=5)
        if self._trace["flap"]:
            self.fleet.set_not_ready(self._trace["flap"], False)

    def stop(self) -> None:
        self.stop_flapping()
