"""Deterministic chaos injection for the API plane, the node fleet,
and the control-plane processes themselves.

`ChaosClient` wraps any `api.client.Client` with seeded, per-verb fault
streams (error rates, injected latency, 429/503 bursts, watch-stream
cuts) — the machinery the chaos soak and the fault-load perf arm run
on. See `injector.py` for the determinism contract.

`NodeFaultPlan`/`NodeChaos` extend the same fixed-draw determinism to
NODE faults — seeded kill / heartbeat-freeze / flap schedules driving a
`kubemark.fleet.HollowFleet` (see `nodes.py`).

`CrashPlan`/`CrashChaos` extend it to PROCESS death: seeded kill points
(in bound-pod progress, not wall time) for the apiserver, the active
scheduler, and the active controller-manager — the durability/HA gates
ride these (see `crash.py` and `kubemark/crash_soak.py`).

`WorkloadPlan`/`WorkloadChaos` extend it to the WORKLOAD itself: a
seeded, time-compressed replay of heterogeneous arrival traces
(diurnal HPA-driven demand, Poisson flash crowds, batch Job waves,
rollout steps, Service churn) — the trace-replay scenario suite rides
these (see `workload.py` and `kubemark/workload_soak.py`).
"""

from .crash import TARGETS as CRASH_TARGETS
from .crash import CrashChaos, CrashPlan
from .injector import VERBS, ChaosClient, ChaosWatcher, FaultPlan
from .nodes import NodeChaos, NodeFaultPlan
from .workload import GENERATORS as WORKLOAD_GENERATORS
from .workload import WorkloadChaos, WorkloadEvent, WorkloadPlan

__all__ = ["ChaosClient", "ChaosWatcher", "CrashChaos", "CrashPlan",
           "CRASH_TARGETS", "FaultPlan", "NodeChaos", "NodeFaultPlan",
           "VERBS", "WORKLOAD_GENERATORS", "WorkloadChaos",
           "WorkloadEvent", "WorkloadPlan"]
