"""Deterministic chaos injection for the API plane.

`ChaosClient` wraps any `api.client.Client` with seeded, per-verb fault
streams (error rates, injected latency, 429/503 bursts, watch-stream
cuts) — the machinery the chaos soak and the fault-load perf arm run
on. See `injector.py` for the determinism contract.
"""

from .injector import VERBS, ChaosClient, ChaosWatcher, FaultPlan

__all__ = ["ChaosClient", "ChaosWatcher", "FaultPlan", "VERBS"]
