"""Deterministic chaos injection for the API plane and the node fleet.

`ChaosClient` wraps any `api.client.Client` with seeded, per-verb fault
streams (error rates, injected latency, 429/503 bursts, watch-stream
cuts) — the machinery the chaos soak and the fault-load perf arm run
on. See `injector.py` for the determinism contract.

`NodeFaultPlan`/`NodeChaos` extend the same fixed-draw determinism to
NODE faults — seeded kill / heartbeat-freeze / flap schedules driving a
`kubemark.fleet.HollowFleet` (see `nodes.py`).
"""

from .injector import VERBS, ChaosClient, ChaosWatcher, FaultPlan
from .nodes import NodeChaos, NodeFaultPlan

__all__ = ["ChaosClient", "ChaosWatcher", "FaultPlan", "NodeChaos",
           "NodeFaultPlan", "VERBS"]
