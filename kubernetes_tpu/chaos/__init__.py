"""Deterministic chaos injection for the API plane, the node fleet,
and the control-plane processes themselves.

`ChaosClient` wraps any `api.client.Client` with seeded, per-verb fault
streams (error rates, injected latency, 429/503 bursts, watch-stream
cuts) — the machinery the chaos soak and the fault-load perf arm run
on. See `injector.py` for the determinism contract.

`NodeFaultPlan`/`NodeChaos` extend the same fixed-draw determinism to
NODE faults — seeded kill / heartbeat-freeze / flap schedules driving a
`kubemark.fleet.HollowFleet` (see `nodes.py`).

`CrashPlan`/`CrashChaos` extend it to PROCESS death: seeded kill points
(in bound-pod progress, not wall time) for the apiserver, the active
scheduler, and the active controller-manager — the durability/HA gates
ride these (see `crash.py` and `kubemark/crash_soak.py`).
"""

from .crash import TARGETS as CRASH_TARGETS
from .crash import CrashChaos, CrashPlan
from .injector import VERBS, ChaosClient, ChaosWatcher, FaultPlan
from .nodes import NodeChaos, NodeFaultPlan

__all__ = ["ChaosClient", "ChaosWatcher", "CrashChaos", "CrashPlan",
           "CRASH_TARGETS", "FaultPlan", "NodeChaos", "NodeFaultPlan",
           "VERBS"]
