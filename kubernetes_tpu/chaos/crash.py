"""Seeded control-plane process-crash injection.

Determinism contract — the same fixed-draw discipline as `FaultPlan`
(injector.py) and `NodeFaultPlan` (nodes.py): every crash target owns
an independent RNG stream seeded from `(plan.seed, target)`, and its
kill point is ONE uniform draw mapped into the plan's progress window,
so WHERE each process dies is a pure function of (seed, target,
workload size) — independent of thread interleaving or how many draws
other streams consumed. `schedule(total)` replays what any live run
with this seed MUST select; `CrashChaos.trace()` records what a run
actually applied, and the crash soak gates on the two being equal
(tests/test_chaos.py), bit-reproducibly across invocations.

Progress is measured in BOUND PODS, not wall time: "kill the apiserver
after the 11th binding" replays exactly, where "kill at t=3.2s" never
would. The soak (kubemark/crash_soak.py) applies each kill as the
bound count crosses its point:

  apiserver            mid-commit-storm; the WAL-backed store recovers
                       (Store.recover) and a fresh server takes the
                       same port — watchers re-list, fleet reconverges
  scheduler            the ACTIVE elector's process dies mid-batch; the
                       standby waits out the lease and binds the
                       remainder (zero duplicate bindings via CAS)
  controller-manager   the active manager dies; the standby resumes
                       replication/eviction under a new fencing term

Reference: the reference grows this as test/e2e/chaosmonkey's
component killer; v1.1 has no equivalent — see DIVERGENCES.md.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, List, Tuple

#: the crashable control-plane processes, in no particular order — the
#: ORDER kills fire in comes from each target's drawn point
TARGETS = ("apiserver", "scheduler", "controller-manager")


@dataclass
class CrashPlan:
    """One seed, one reproducible crash schedule."""

    seed: int = 0
    targets: Tuple[str, ...] = TARGETS
    #: each kill point lands in [window[0], window[1]) of the workload
    window: Tuple[float, float] = (0.25, 0.8)

    def stream(self, target: str) -> random.Random:
        # str seeding hashes via sha512 — stable across processes
        # (same rule as FaultPlan.stream / NodeFaultPlan.stream)
        return random.Random(f"{self.seed}:crash:{target}")

    def fraction(self, target: str) -> float:
        """The target's kill point as a workload fraction: exactly ONE
        draw from its stream, always."""
        lo, hi = self.window
        return lo + self.stream(target).random() * (hi - lo)

    def kill_point(self, target: str, total: int) -> int:
        """Bound-pod count at which the target dies. Clamped inside
        (0, total) so every kill observably interrupts the run."""
        return min(max(int(self.fraction(target) * total), 1), total - 1)

    def schedule(self, total: int) -> Dict[str, int]:
        """What a live run with this seed MUST select — the pure replay
        the reproducibility gate compares a trace against."""
        return {t: self.kill_point(t, total) for t in self.targets}

    def order(self, total: int) -> List[Tuple[int, str]]:
        """Kill events sorted by firing point (ties broken by target
        name, deterministically)."""
        return sorted((p, t) for t, p in self.schedule(total).items())


class CrashChaos:
    """Apply a CrashPlan, recording a trace of what actually fired."""

    def __init__(self, plan: CrashPlan, total: int):
        self.plan = plan
        self.total = total
        self._trace: Dict[str, int] = {}

    def pending(self) -> List[Tuple[int, str]]:
        """Kill events not yet applied, in firing order."""
        return [(p, t) for p, t in self.plan.order(self.total)
                if t not in self._trace]

    def record(self, target: str, point: int) -> None:
        self._trace[target] = point

    def trace(self) -> Dict[str, int]:
        """Kill points actually applied — a run is reproducible when
        this equals plan.schedule(total) for every fired target."""
        return dict(self._trace)


@dataclass
class ShardKillPlan:
    """Seeded MESH-shard kill schedule: which shard owners die, and at
    which bound-pod count — the data-plane sibling of CrashPlan's
    control-plane kills, under the identical determinism contract.

    Each shard owns an independent stream seeded from
    `(seed, "shard", index)`, and that stream is drawn from exactly
    ONCE; the single uniform serves both decisions. Victim selection:
    the `kills` shards with the SMALLEST draws (ties by index) die —
    every shard's fate is a pure function of (seed, n_shards, kills),
    independent of interleaving. Kill point: the victim's same draw
    maps into the progress window, measured in BOUND PODS like every
    other plan (replays exactly; wall time never would).
    `schedule(total)` is the pure replay the shard-kill soak
    (kubemark/shard_soak.py) gates a live trace against."""

    seed: int = 0
    n_shards: int = 4
    kills: int = 1
    #: each kill point lands in [window[0], window[1]) of the workload
    window: Tuple[float, float] = (0.25, 0.8)

    def stream(self, shard: int) -> random.Random:
        # str seeding hashes via sha512 — stable across processes
        return random.Random(f"{self.seed}:shard:{shard}")

    def draw(self, shard: int) -> float:
        """The shard's ONE uniform draw, always."""
        return self.stream(shard).random()

    def victims(self) -> Tuple[int, ...]:
        """The shards that die: smallest draws first, ties by index,
        ascending shard order in the result."""
        k = max(0, min(self.kills, self.n_shards - 1))
        ranked = sorted(range(self.n_shards),
                        key=lambda s: (self.draw(s), s))
        return tuple(sorted(ranked[:k]))

    def fraction(self, shard: int) -> float:
        lo, hi = self.window
        return lo + self.draw(shard) * (hi - lo)

    def kill_point(self, shard: int, total: int) -> int:
        """Bound-pod count at which the shard's owner dies. Clamped
        inside (0, total) so the kill observably interrupts the run."""
        return min(max(int(self.fraction(shard) * total), 1), total - 1)

    def schedule(self, total: int) -> Dict[int, int]:
        """What a live run with this seed MUST select."""
        return {s: self.kill_point(s, total) for s in self.victims()}

    def order(self, total: int) -> List[Tuple[int, int]]:
        """Kill events sorted by firing point (ties by shard index)."""
        return sorted((p, s) for s, p in self.schedule(total).items())


class ShardKillChaos:
    """Apply a ShardKillPlan, recording a trace of what actually fired
    — same reproducibility gate shape as CrashChaos."""

    def __init__(self, plan: ShardKillPlan, total: int):
        self.plan = plan
        self.total = total
        self._trace: Dict[int, int] = {}

    def pending(self) -> List[Tuple[int, int]]:
        """Kill events not yet applied, in firing order."""
        return [(p, s) for p, s in self.plan.order(self.total)
                if s not in self._trace]

    def record(self, shard: int, point: int) -> None:
        self._trace[shard] = point

    def trace(self) -> Dict[int, int]:
        """Kill points actually applied — reproducible when equal to
        plan.schedule(total) for every fired shard."""
        return dict(self._trace)
