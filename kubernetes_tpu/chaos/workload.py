"""Seeded trace-replay workload generation: a day of heterogeneous
production traffic, compressed into a deterministic event stream.

Determinism contract — the same fixed-draw discipline as `FaultPlan`
(injector.py), `NodeFaultPlan` (nodes.py) and `CrashPlan` (crash.py):
every generator owns an independent RNG stream seeded from
`(plan.seed, "workload", generator)`, and each replay tick consumes a
FIXED number of draws per generator — so the event a generator emits
at tick t is a pure function of (seed, generator, t), independent of
which branches other ticks or other generators took. `schedule()`
replays the whole trace purely; `WorkloadChaos.trace()` records what a
live run actually applied, and the workload soak gates on the two
being byte-identical (tests/test_workload.py).

The replay CLOCK is the compressed tick axis, not wall time: a trace
is defined over `ticks` virtual steps (a "day" at whatever resolution
the plan chooses), and the soak maps ticks onto wall seconds with a
compression factor. Wall timing — how long the apiserver takes, where
the GIL slices land — is explicitly outside the contract, exactly like
NodeFaultPlan's flap-toggle phase (see DIVERGENCES.md).

The six generators model the heterogeneous-workload regime Gavel
(PAPERS.md) argues schedulers must be evaluated under:

  diurnal   a sinusoid of per-Deployment demand (user traffic) that
            the HPA chases up and down through the scale subresource
  burst     Poisson flash crowds: batches of bare pods whose
            time-to-bind during the burst window is an SLO gate
  jobwave   batch Job waves (parallelism/completions drawn per wave;
            a drawn fraction of waves crash-loop, exercising the Job
            controller's failure backoff)
  rollout   Deployment template bumps (hash-based rolling update) and
            DaemonSet retargeting steps
  churn     Service create/delete churn against a fixed name pool
  drain     low-priority batch fill waves that saturate the fleet,
            then ONE high-priority surge (drawn tick in the second
            half of the day) — the flash-crowd drain scenario the
            preemption soak gates on (surge pods bind by evicting
            fill pods; sched/preemption.py)

Reference: the reference grows this as test/e2e's load/density
generators (RunRC + load.go's traffic shapes); v1.1 has no equivalent
replayable trace engine — see DIVERGENCES.md.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..utils.clock import REAL, Clock

#: generator evaluation order inside one tick (ties in the merged
#: stream break by this order, deterministically)
GENERATORS = ("diurnal", "burst", "jobwave", "rollout", "churn", "drain")


@dataclass(frozen=True)
class WorkloadEvent:
    """One replayable workload action. Frozen + tuple params so event
    streams compare bit-for-bit across invocations."""

    tick: int
    generator: str
    action: str
    target: str = ""
    value: int = 0
    params: Tuple[int, ...] = ()


@dataclass
class WorkloadPlan:
    """One seed, one reproducible day of traffic."""

    seed: int = 0
    #: virtual steps in the replay (the compressed "day" axis)
    ticks: int = 24
    # ---- diurnal: demand = base + amp * sin(2pi * (t/period + phase))
    diurnal_base: int = 30
    diurnal_amp: int = 20
    diurnal_period: int = 24
    diurnal_noise: int = 2
    deployment: str = "web"
    # ---- burst: flash crowds of bare pods
    burst_rate: float = 0.15
    burst_min: int = 8
    burst_max: int = 24
    # ---- jobwave: batch Job waves
    jobwave_rate: float = 0.2
    jobwave_max_parallelism: int = 3
    jobwave_max_extra_completions: int = 3
    jobwave_fail_fraction: float = 0.25
    # ---- rollout: Deployment image bumps + DaemonSet retargeting
    rollout_rate: float = 0.12
    daemonset: str = "agent"
    n_zones: int = 4
    # ---- churn: Service create/delete against a fixed pool
    churn_rate: float = 0.5
    service_pool: int = 6
    # ---- drain: low-priority batch fill waves + one high-priority
    # surge. Defaults are crowd-pod sized (10m/16Mi) so the generator
    # rides along in every workload soak without saturating anything;
    # the flash-drain soak passes fleet-saturating requests explicitly.
    drain_fill_rate: float = 0.3
    drain_fill_min: int = 4
    drain_fill_max: int = 12
    drain_fill_priority: int = -100
    drain_fill_cpu_milli: int = 10
    drain_fill_mem_mi: int = 16
    drain_surge_min: int = 4
    drain_surge_max: int = 12
    drain_surge_priority: int = 1000
    drain_surge_cpu_milli: int = 10
    drain_surge_mem_mi: int = 16

    def stream(self, generator: str) -> random.Random:
        # str seeding hashes via sha512 — stable across processes
        # (same rule as FaultPlan/NodeFaultPlan/CrashPlan.stream)
        return random.Random(f"{self.seed}:workload:{generator}")

    # ------------------------------------------------------- generators
    #
    # Each consumes a FIXED number of draws per tick (noted per
    # generator), so tick t's event never depends on earlier branches.

    def _diurnal(self) -> List[WorkloadEvent]:
        """1 setup draw (phase) + 1 draw/tick (noise)."""
        rng = self.stream("diurnal")
        phase = rng.random()
        out = []
        for t in range(self.ticks):
            noise = (rng.random() * 2.0 - 1.0) * self.diurnal_noise
            demand = self.diurnal_base + self.diurnal_amp * math.sin(
                2.0 * math.pi * (t / max(1, self.diurnal_period) + phase))
            out.append(WorkloadEvent(
                tick=t, generator="diurnal", action="demand",
                target=self.deployment,
                value=max(0, int(round(demand + noise)))))
        return out

    def _burst(self) -> List[WorkloadEvent]:
        """2 draws/tick (start?, size)."""
        rng = self.stream("burst")
        out = []
        for t in range(self.ticks):
            r_start, r_size = rng.random(), rng.random()
            if r_start < self.burst_rate:
                span = self.burst_max - self.burst_min + 1
                out.append(WorkloadEvent(
                    tick=t, generator="burst", action="crowd",
                    target=f"crowd-{t:03d}",
                    value=self.burst_min + int(r_size * span) % span))
        return out

    def _jobwave(self) -> List[WorkloadEvent]:
        """4 draws/tick (start?, parallelism, completions, failing?)."""
        rng = self.stream("jobwave")
        out = []
        for t in range(self.ticks):
            r_start, r_par, r_comp, r_fail = (rng.random(), rng.random(),
                                              rng.random(), rng.random())
            if r_start < self.jobwave_rate:
                par = 1 + int(r_par * self.jobwave_max_parallelism) \
                    % self.jobwave_max_parallelism
                completions = par + int(
                    r_comp * (self.jobwave_max_extra_completions + 1)) \
                    % (self.jobwave_max_extra_completions + 1)
                failing = 1 if r_fail < self.jobwave_fail_fraction else 0
                out.append(WorkloadEvent(
                    tick=t, generator="jobwave", action="job",
                    target=f"wave-{t:03d}", value=completions,
                    params=(par, failing)))
        return out

    def _rollout(self) -> List[WorkloadEvent]:
        """3 draws/tick (step?, kind, param). Deployment image versions
        are the running count of prior deploy steps (pure)."""
        rng = self.stream("rollout")
        out = []
        version = 1
        for t in range(self.ticks):
            r_step, r_kind, r_param = (rng.random(), rng.random(),
                                       rng.random())
            if r_step >= self.rollout_rate:
                continue
            if r_kind < 0.5:
                version += 1
                out.append(WorkloadEvent(
                    tick=t, generator="rollout", action="deploy_image",
                    target=self.deployment, value=version))
            else:
                # zone -1 clears the selector (daemons on every node)
                zone = int(r_param * (self.n_zones + 1)) \
                    % (self.n_zones + 1) - 1
                out.append(WorkloadEvent(
                    tick=t, generator="rollout", action="ds_retarget",
                    target=self.daemonset, value=zone))
        return out

    def _churn(self) -> List[WorkloadEvent]:
        """3 draws/tick (act?, create-vs-delete, index)."""
        rng = self.stream("churn")
        out = []
        for t in range(self.ticks):
            r_act, r_kind, r_idx = (rng.random(), rng.random(),
                                    rng.random())
            if r_act < self.churn_rate:
                idx = int(r_idx * self.service_pool) % self.service_pool
                action = "svc_create" if r_kind < 0.5 else "svc_delete"
                out.append(WorkloadEvent(
                    tick=t, generator="churn", action=action,
                    target=f"svc-{idx}"))
        return out

    def _drain(self) -> List[WorkloadEvent]:
        """1 setup draw (surge tick) + 3 draws/tick (fill?, fill size,
        surge size). Event params carry (priority, cpu_milli, mem_mi)
        so the trace pins the exact pods a replay must create."""
        rng = self.stream("drain")
        half = self.ticks // 2
        span_t = max(1, self.ticks - half)
        surge_tick = half + int(rng.random() * span_t) % span_t
        out = []
        for t in range(self.ticks):
            r_fill, r_fsize, r_ssize = (rng.random(), rng.random(),
                                        rng.random())
            if r_fill < self.drain_fill_rate:
                span = self.drain_fill_max - self.drain_fill_min + 1
                out.append(WorkloadEvent(
                    tick=t, generator="drain", action="batch_fill",
                    target=f"fill-{t:03d}",
                    value=self.drain_fill_min + int(r_fsize * span) % span,
                    params=(self.drain_fill_priority,
                            self.drain_fill_cpu_milli,
                            self.drain_fill_mem_mi)))
            if t == surge_tick:
                span = self.drain_surge_max - self.drain_surge_min + 1
                out.append(WorkloadEvent(
                    tick=t, generator="drain", action="surge",
                    target=f"surge-{t:03d}",
                    value=self.drain_surge_min + int(r_ssize * span) % span,
                    params=(self.drain_surge_priority,
                            self.drain_surge_cpu_milli,
                            self.drain_surge_mem_mi)))
        return out

    def surge_tick(self) -> int:
        """The tick the drain surge lands at (pure) — the flash-drain
        soak keys its SLO trip window off it."""
        for ev in self._drain():
            if ev.action == "surge":
                return ev.tick
        return self.ticks  # unreachable for ticks >= 1

    # ----------------------------------------------------------- replay

    def schedule(self) -> Dict[str, List[WorkloadEvent]]:
        """The full trace, replayed purely — what any live run with
        this seed MUST apply, per generator stream."""
        return {"diurnal": self._diurnal(), "burst": self._burst(),
                "jobwave": self._jobwave(), "rollout": self._rollout(),
                "churn": self._churn(), "drain": self._drain()}

    def events(self) -> List[WorkloadEvent]:
        """The merged stream, ordered by (tick, generator order) — the
        order `WorkloadChaos.apply_tick` applies events in."""
        sched = self.schedule()
        rank = {g: i for i, g in enumerate(GENERATORS)}
        return sorted((ev for evs in sched.values() for ev in evs),
                      key=lambda e: (e.tick, rank[e.generator]))

    def demand_curve(self) -> List[int]:
        """Per-tick diurnal demand (pure) — what the HPA convergence
        gate compares replica counts against."""
        return [ev.value for ev in self._diurnal()]

    def expected_services(self) -> List[str]:
        """The service set a full replay must end with (pure fold of
        the churn stream) — a state-equality gate both same-seed
        invocations are compared against."""
        live: set = set()
        for ev in self._churn():
            if ev.action == "svc_create":
                live.add(ev.target)
            else:
                live.discard(ev.target)
        return sorted(live)

    def final_ds_selector(self) -> Optional[int]:
        """The DaemonSet zone the replay ends retargeted at (-1 = all
        nodes), or None when the rollout stream never retargets."""
        zone = None
        for ev in self._rollout():
            if ev.action == "ds_retarget":
                zone = ev.value
        return zone


class WorkloadChaos:
    """Apply a WorkloadPlan against a cluster, recording a trace.

    The applier is intentionally thin: it owns WHAT happens (object
    creates/updates/deletes in plan order, retried through injected API
    faults until they land) and records it; the soak harness owns the
    surrounding cluster and the SLO measurement. `demand` is the shared
    diurnal demand signal the harness wires into the HPA's metrics
    source."""

    def __init__(self, client, plan: WorkloadPlan,
                 namespace: str = "default",
                 clock: Optional[Clock] = None):
        self.client = client
        self.plan = plan
        self.namespace = namespace
        self.clock = clock or REAL
        self.demand = plan.diurnal_base  # pre-replay demand floor
        self._by_tick: Dict[int, List[WorkloadEvent]] = {}
        for ev in plan.events():
            self._by_tick.setdefault(ev.tick, []).append(ev)
        self._trace: Dict[str, List[WorkloadEvent]] = \
            {g: [] for g in GENERATORS}
        #: crowd pods created, in creation order (the burst-window
        #: bind-SLO population)
        self.crowd_pods: List[str] = []
        #: jobs created -> (completions, failing)
        self.jobs: Dict[str, Tuple[int, bool]] = {}
        #: optional hook(names) fired the moment a crowd batch lands —
        #: the soak stamps burst-pod creation times here, so the
        #: bind-latency SLO clock starts at the POST, not at a poll
        self.on_crowd = None
        #: drain-generator state, same shape: fill pods and surge pods
        #: in creation order, plus the surge hook the flash-drain soak
        #: stamps surge-bind SLO clocks with
        self.drain_pods: List[str] = []
        self.surge_pods: List[str] = []
        self.on_surge = None

    def trace(self) -> Dict[str, List[WorkloadEvent]]:
        """Events actually applied, per generator, in apply order — a
        run is reproducible when this equals plan.schedule() for every
        tick the run replayed."""
        return {g: list(evs) for g, evs in self._trace.items()}

    def apply_tick(self, tick: int, deadline: float,
                   generators=None) -> List[WorkloadEvent]:
        """Apply every event of one tick, in merged-stream order. Each
        apply retries through injected faults until it lands or the
        deadline (on this applier's clock.monotonic() axis) passes —
        an event that never lands leaves the trace short, which the
        schedule-replay gate then correctly fails. `generators`
        restricts the replay to a subset of streams (the flash-drain
        soak replays only "drain"; its reproducibility gate then
        compares only that stream's trace)."""
        applied = []
        for ev in self._by_tick.get(tick, ()):
            if generators is not None and ev.generator not in generators:
                continue
            while True:
                try:
                    self._apply(ev)
                except Exception:
                    if self.clock.monotonic() > deadline:
                        return applied
                    self.clock.sleep(0.02)
                    continue
                self._trace[ev.generator].append(ev)
                applied.append(ev)
                break
        return applied

    # ------------------------------------------------------ event verbs

    def _apply(self, ev: WorkloadEvent) -> None:
        from ..core import types as api
        from ..core.errors import AlreadyExists, NotFound
        ns = self.namespace
        if ev.action == "demand":
            self.demand = ev.value
        elif ev.action == "crowd":
            names = [f"{ev.target}-{i:03d}" for i in range(ev.value)]
            pods = [p for p in (self._crowd_pod(n) for n in names)
                    if p is not None]
            if pods:
                self.client.create_batch("pods", pods, ns)
            created = [n for n in names if n not in set(self.crowd_pods)]
            self.crowd_pods.extend(created)
            if self.on_crowd and created:
                self.on_crowd(created)
        elif ev.action == "job":
            par, failing = ev.params
            labels = {"wave": ev.target}
            try:
                self.client.create("jobs", api.Job(
                    metadata=api.ObjectMeta(
                        name=ev.target, namespace=ns,
                        labels={"failing": str(failing)}),
                    spec=api.JobSpec(
                        parallelism=par, completions=ev.value,
                        selector=labels,
                        template=api.PodTemplateSpec(
                            metadata=api.ObjectMeta(labels=dict(labels)),
                            spec=self._tiny_pod_spec()))), ns)
            except AlreadyExists:
                pass  # landed on a retried apply
            self.jobs[ev.target] = (ev.value, bool(failing))
        elif ev.action == "deploy_image":
            d = self.client.get("deployments", ev.target, ns)
            from dataclasses import replace
            tpl = d.spec.template
            spec = replace(tpl.spec, containers=[
                replace(c, image=f"img:v{ev.value}")
                for c in tpl.spec.containers])
            self.client.update("deployments", replace(
                d, spec=replace(d.spec, template=replace(
                    tpl, spec=spec))), ns)
        elif ev.action == "ds_retarget":
            ds = self.client.get("daemonsets", ev.target, ns)
            from dataclasses import replace
            sel = {} if ev.value < 0 else {"zone": f"z{ev.value}"}
            tpl = ds.spec.template
            self.client.update("daemonsets", replace(
                ds, spec=replace(ds.spec, template=replace(
                    tpl, spec=replace(tpl.spec, node_selector=sel)))), ns)
        elif ev.action in ("batch_fill", "surge"):
            prio, cpu_m, mem_mi = ev.params
            surge = ev.action == "surge"
            seen_list = self.surge_pods if surge else self.drain_pods
            seen = set(seen_list)
            names = [f"{ev.target}-{i:03d}" for i in range(ev.value)]
            labels = {"surge": "1"} if surge else {"drain": "1"}
            pods = [self._drain_pod(n, prio, cpu_m, mem_mi, labels)
                    for n in names if n not in seen]
            if pods:
                self.client.create_batch("pods", pods, ns)
            created = [n for n in names if n not in seen]
            seen_list.extend(created)
            if surge and self.on_surge and created:
                self.on_surge(created)
        elif ev.action == "svc_create":
            try:
                self.client.create("services", api.Service(
                    metadata=api.ObjectMeta(name=ev.target, namespace=ns),
                    spec=api.ServiceSpec(
                        selector={"app": ev.target},
                        ports=[api.ServicePort(port=80)])), ns)
            except AlreadyExists:
                pass  # churn drew a create for a live name: a no-op
        elif ev.action == "svc_delete":
            try:
                self.client.delete("services", ev.target, ns)
            except NotFound:
                pass  # churn drew a delete for a dead name: a no-op
        else:  # pragma: no cover - plan and applier are one module
            raise ValueError(f"unknown workload action {ev.action!r}")

    def _crowd_pod(self, name: str):
        from ..core import types as api
        from ..core.quantity import parse_quantity
        if name in self.crowd_pods:
            return None  # landed on a retried apply
        return api.Pod(
            metadata=api.ObjectMeta(name=name, namespace=self.namespace,
                                    labels={"crowd": "1"}),
            spec=api.PodSpec(containers=[api.Container(
                name="c", image="img",
                resources=api.ResourceRequirements(
                    requests={"cpu": parse_quantity("10m"),
                              "memory": parse_quantity("16Mi")}))]),
            status=api.PodStatus(phase="Pending"))

    def _drain_pod(self, name: str, prio: int, cpu_m: int, mem_mi: int,
                   labels: Dict[str, str]):
        from ..core import types as api
        from ..core.quantity import parse_quantity
        return api.Pod(
            metadata=api.ObjectMeta(name=name, namespace=self.namespace,
                                    labels=dict(labels)),
            spec=api.PodSpec(priority=prio, containers=[api.Container(
                name="c", image="img",
                resources=api.ResourceRequirements(
                    requests={"cpu": parse_quantity(f"{cpu_m}m"),
                              "memory": parse_quantity(f"{mem_mi}Mi")}))]),
            status=api.PodStatus(phase="Pending"))

    def _tiny_pod_spec(self):
        from ..core import types as api
        from ..core.quantity import parse_quantity
        return api.PodSpec(containers=[api.Container(
            name="c", image="img",
            resources=api.ResourceRequirements(
                requests={"cpu": parse_quantity("10m"),
                          "memory": parse_quantity("16Mi")}))])
