"""Seeded, deterministic fault injection for any Client.

Determinism contract: every verb owns an independent RNG stream seeded
from `(plan.seed, verb)`, and each decision consumes a FIXED number of
draws — so the i-th call of a verb always gets the same (fault, delay)
decision for a given seed, regardless of how threads interleave calls
across verbs. `FaultPlan.schedule(verb, n)` replays the first n
decisions of a stream purely, and `ChaosClient.trace()` returns what a
live run actually drew — a run is reproducible when its trace equals
the schedule prefix (asserted by the chaos soak; see tests/test_chaos.py).

Faults fire on the REQUEST path, before the wrapped client is invoked:
an injected connection loss is a cleanly-lost request (the server never
saw it), so the soak's convergence invariants are about component
recovery, not about ambiguous-commit semantics — the retry matrix
(tests/test_retry.py) owns those.

Reference: the reference grows this as test/e2e/chaosmonkey; client-go
has no equivalent client wrapper (DIVERGENCES.md).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..api.client import Client
from ..core.errors import ServiceUnavailable, TooManyRequests
from ..core.watch import Event, Watcher
from ..utils.clock import REAL, Clock

#: the injectable verb streams; batch/columnar variants draw from their
#: base verb's stream so a workload's fault schedule doesn't depend on
#: which transport shape (single vs batch) a component happens to use
VERBS = ("create", "get", "list", "update", "update_status", "patch",
         "delete", "watch", "bind")

_FAULT_CONNECTION = "connection"
_FAULT_429 = "429"
_FAULT_503 = "503"


@dataclass
class FaultPlan:
    """One seed, one reproducible fault schedule."""

    seed: int = 0
    #: probability an injectable call draws a fault (uniform over `faults`)
    error_rate: float = 0.0
    #: per-verb overrides of error_rate, e.g. {"watch": 0.2}
    verb_rates: Dict[str, float] = field(default_factory=dict)
    #: fault mix drawn from on a fault hit
    faults: Tuple[str, ...] = (_FAULT_CONNECTION, _FAULT_429, _FAULT_503)
    #: probability a call sleeps, and the max injected sleep (uniform)
    latency_rate: float = 0.0
    latency: float = 0.0
    #: cut every watch stream (ERROR + failed flag) after N delivered
    #: events; None = streams run until stopped or force-cut
    watch_cut_after: Optional[int] = None
    #: Retry-After seconds carried by injected 429s
    retry_after: float = 0.05

    def rate_for(self, verb: str) -> float:
        return self.verb_rates.get(verb, self.error_rate)

    def stream(self, verb: str) -> random.Random:
        # str seeding hashes via sha512 — stable across processes
        # (unlike hash(), which PYTHONHASHSEED salts)
        return random.Random(f"{self.seed}:{verb}")

    def draw(self, rng: random.Random, rate: float
             ) -> Tuple[Optional[str], float]:
        """One decision. Exactly four draws ALWAYS, so a decision is a
        pure function of (seed, verb, call index) — never of which
        branches earlier decisions took."""
        r_fault, r_pick = rng.random(), rng.random()
        r_lat, r_delay = rng.random(), rng.random()
        fault = None
        if self.faults and r_fault < rate:
            fault = self.faults[int(r_pick * len(self.faults))
                                % len(self.faults)]
        delay = 0.0
        if self.latency > 0 and r_lat < self.latency_rate:
            delay = r_delay * self.latency
        return fault, delay

    def schedule(self, verb: str, n: int) -> List[Optional[str]]:
        """The first n fault decisions of a verb's stream, replayed
        purely — what any run with this seed MUST have drawn."""
        rng = self.stream(verb)
        rate = self.rate_for(verb)
        return [self.draw(rng, rate)[0] for _ in range(n)]


class ChaosWatcher(Watcher):
    """Pass-through watcher that can be cut: after `cut_after` events
    (or a forced `cut()`), it reports an ERROR event and a `failed`
    flag — exactly the wire a mid-stream disconnect leaves behind, so
    reflectors exercise their reconnect path."""

    def __init__(self, inner: Watcher, cut_after: Optional[int] = None,
                 capacity: int = 100_000):
        super().__init__(capacity)
        self.inner = inner
        self.failed = False
        self._cut_after = cut_after
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def cut(self) -> None:
        """Force a mid-stream disconnect now."""
        self.failed = True
        self.send(Event("ERROR", ServiceUnavailable(
            "chaos: watch stream cut")))
        self.inner.stop()
        super().stop()

    def _pump(self):
        n = 0
        for ev in self.inner:
            if not self.send(ev):
                break
            n += 1
            if self._cut_after is not None and n >= self._cut_after:
                self.cut()
                return
        # propagate how the inner stream ended (an HTTP watcher's
        # failed flag must not be laundered into a clean stop)
        self.failed = self.failed or getattr(self.inner, "failed", False)
        self.inner.stop()
        super().stop()

    def stop(self) -> None:
        self.inner.stop()
        super().stop()


class ChaosClient(Client):
    """Wrap any Client with the plan's fault streams. Thread-safe; all
    non-verb capabilities delegate untouched."""

    def __init__(self, inner: Client, plan: FaultPlan,
                 clock: Optional[Clock] = None):
        self.inner = inner
        self.plan = plan
        # injected latency sleeps ride the clock so a FakeClock harness
        # can compress a latency-heavy plan without wall time passing
        self.clock = clock or REAL
        self._lock = threading.Lock()
        self._streams = {v: plan.stream(v) for v in VERBS}
        self._trace: Dict[str, List[Optional[str]]] = {v: [] for v in VERBS}
        self._watchers: List[ChaosWatcher] = []

    # ------------------------------------------------------------ controls

    def trace(self) -> Dict[str, List[Optional[str]]]:
        """Per-verb fault decisions actually drawn, in draw order."""
        with self._lock:
            return {v: list(t) for v, t in self._trace.items()}

    def cut_watches(self) -> int:
        """Force-cut every live watch stream (the 'apiserver dropped
        its connections' moment). Returns how many were cut."""
        with self._lock:
            live = [w for w in self._watchers if not w.stopped]
            self._watchers = []
        for w in live:
            w.cut()
        return len(live)

    # ------------------------------------------------------------ plumbing

    def _inject(self, verb: str) -> None:
        with self._lock:
            rng = self._streams[verb]
            fault, delay = self.plan.draw(rng, self.plan.rate_for(verb))
            self._trace[verb].append(fault)
        if delay > 0:
            self.clock.sleep(delay)
        if fault == _FAULT_429:
            err = TooManyRequests("chaos: injected 429 burst")
            err.retry_after = self.plan.retry_after
            raise err
        if fault == _FAULT_503:
            raise ServiceUnavailable("chaos: injected 503")
        if fault == _FAULT_CONNECTION:
            raise ConnectionError("chaos: injected connection loss")

    # --------------------------------------------------------------- verbs

    def create(self, resource, obj, namespace=""):
        self._inject("create")
        return self.inner.create(resource, obj, namespace)

    def create_batch(self, resource, objs, namespace=""):
        self._inject("create")
        return self.inner.create_batch(resource, objs, namespace)

    def create_from_template(self, resource, template, names, namespace=""):
        self._inject("create")
        return self.inner.create_from_template(resource, template, names,
                                               namespace)

    def get(self, resource, name, namespace=""):
        self._inject("get")
        return self.inner.get(resource, name, namespace)

    def get_scale(self, resource, name, namespace=""):
        self._inject("get")
        return self.inner.get_scale(resource, name, namespace)

    def list(self, resource, namespace="", label_selector="",
             field_selector=""):
        self._inject("list")
        return self.inner.list(resource, namespace, label_selector,
                               field_selector)

    def update(self, resource, obj, namespace=""):
        self._inject("update")
        return self.inner.update(resource, obj, namespace)

    def update_scale(self, resource, name, scale, namespace=""):
        self._inject("update")
        return self.inner.update_scale(resource, name, scale, namespace)

    def finalize_namespace(self, obj):
        self._inject("update")
        return self.inner.finalize_namespace(obj)

    def update_status(self, resource, obj, namespace=""):
        self._inject("update_status")
        return self.inner.update_status(resource, obj, namespace)

    def update_status_batch(self, resource, objs, namespace=""):
        self._inject("update_status")
        return self.inner.update_status_batch(resource, objs, namespace)

    def patch(self, resource, name, patch_body, namespace="",
              patch_type="application/strategic-merge-patch+json"):
        self._inject("patch")
        return self.inner.patch(resource, name, patch_body, namespace,
                                patch_type)

    def delete(self, resource, name, namespace="",
               grace_period_seconds=None, uid=None):
        self._inject("delete")
        return self.inner.delete(
            resource, name, namespace,
            grace_period_seconds=grace_period_seconds, uid=uid)

    def bind(self, binding, namespace=""):
        self._inject("bind")
        return self.inner.bind(binding, namespace)

    def bind_batch(self, bindings, namespace=""):
        self._inject("bind")
        return self.inner.bind_batch(bindings, namespace)

    def bind_batch_hosts(self, assignments):
        self._inject("bind")
        return self.inner.bind_batch_hosts(assignments)

    def watch(self, resource, namespace="", since_rev=None,
              label_selector="", field_selector=""):
        self._inject("watch")
        inner = self.inner.watch(resource, namespace, since_rev,
                                 label_selector, field_selector)
        w = ChaosWatcher(inner, cut_after=self.plan.watch_cut_after)
        with self._lock:
            self._watchers = [x for x in self._watchers
                              if not x.stopped] + [w]
        return w

    # -------------------------------------------- untouched capabilities

    def pod_logs(self, name, namespace="default", container="",
                 tail_lines=0, previous=False):
        return self.inner.pod_logs(name, namespace, container,
                                   tail_lines, previous)

    def pod_logs_stream(self, name, namespace="default", container=""):
        return self.inner.pod_logs_stream(name, namespace, container)

    def node_proxy(self, node_name, path):
        return self.inner.node_proxy(node_name, path)

    def __getattr__(self, name: str) -> Any:
        # transport extras (portforward_open, registry, ...) delegate;
        # __getattr__ only fires for names not found on ChaosClient
        return getattr(self.inner, name)
