"""VolumePlugin interface, manager, and the plugin set.

Reference: pkg/volume/plugins.go (VolumePlugin, VolumePluginMgr
InitPlugins/FindPluginBySpec) and pkg/volume/volume.go (Builder SetUp /
GetPath, Cleaner TearDown). Pod volume directories follow the kubelet
layout: <root>/pods/<uid>/volumes/<plugin>/<volume-name>.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Dict, List, Optional

from ..core import types as api
from ..core.errors import BadRequest


class VolumeHost:
    """What plugins get from their host (ref: plugins.go VolumeHost):
    the kubelet root dir, an API client for secret fetch, and the cloud
    provider for attach/detach."""

    def __init__(self, root_dir: str, client=None, cloud=None):
        self.root_dir = root_dir
        self.client = client
        self.cloud = cloud

    def pod_volume_dir(self, pod_uid: str, plugin_name: str,
                       volume_name: str) -> str:
        safe_plugin = plugin_name.replace("/", "~")
        path = os.path.join(self.root_dir, "pods", pod_uid, "volumes",
                            safe_plugin, volume_name)
        # Defense in depth behind validate_pod's DNS-1123 volume-name check:
        # a traversal-shaped uid/name must never resolve outside root_dir
        # (tear_down rmtree's this path).
        root = os.path.realpath(self.root_dir)
        if not os.path.realpath(path).startswith(root + os.sep):
            raise BadRequest(
                f"volume path {path!r} escapes kubelet root {root!r}")
        return path


class Builder:
    """(ref: volume.Builder — SetUp + GetPath)"""

    def set_up(self) -> None:
        raise NotImplementedError

    def get_path(self) -> str:
        raise NotImplementedError


class Cleaner:
    """(ref: volume.Cleaner — TearDown)"""

    def tear_down(self) -> None:
        raise NotImplementedError


class VolumePlugin:
    name = ""

    def init(self, host: VolumeHost) -> None:
        self.host = host

    def can_support(self, volume: api.Volume) -> bool:
        raise NotImplementedError

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        raise NotImplementedError

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        raise NotImplementedError

    def new_cleaner_from_spec(self, volume: api.Volume,
                              pod: api.Pod) -> Cleaner:
        """Spec-aware teardown: plugins that delegate (persistent claims)
        or hold external state (cloud disk attach) override this; the
        default routes to the name/uid cleaner."""
        return self.new_cleaner(volume.name, pod.metadata.uid)


class _DirBuilder(Builder, Cleaner):
    """Shared directory-backed builder/cleaner."""

    def __init__(self, path: str):
        self.path = path

    def set_up(self) -> None:
        os.makedirs(self.path, exist_ok=True)

    def get_path(self) -> str:
        return self.path

    def tear_down(self) -> None:
        shutil.rmtree(self.path, ignore_errors=True)


# ------------------------------------------------------------ local plugins

class EmptyDirPlugin(VolumePlugin):
    """(ref: pkg/volume/empty_dir)"""
    name = "kubernetes.io/empty-dir"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.empty_dir is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        return _DirBuilder(self.host.pod_volume_dir(
            pod.metadata.uid, self.name, volume.name))

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self.host.pod_volume_dir(
            pod_uid, self.name, volume_name))


class _HostPathBuilder(Builder, Cleaner):
    def __init__(self, path: str):
        self.path = path

    def set_up(self) -> None:
        pass  # the path exists (or not) on the host; nothing to create

    def get_path(self) -> str:
        return self.path

    def tear_down(self) -> None:
        pass  # never delete host paths


class HostPathPlugin(VolumePlugin):
    """(ref: pkg/volume/host_path)"""
    name = "kubernetes.io/host-path"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.host_path is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        return _HostPathBuilder(volume.host_path.path)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _HostPathBuilder("")


class _SecretBuilder(_DirBuilder):
    def __init__(self, path: str, plugin: "SecretPlugin",
                 volume: api.Volume, pod: api.Pod):
        super().__init__(path)
        self.plugin = plugin
        self.volume = volume
        self.pod = pod

    def set_up(self) -> None:
        super().set_up()
        client = self.plugin.host.client
        if client is None:
            raise BadRequest("secret volumes need an API client")
        secret = client.get("secrets", self.volume.secret.secret_name,
                            self.pod.metadata.namespace)
        for key, value in secret.data.items():
            with open(os.path.join(self.path, key), "w") as f:
                f.write(value)


class SecretPlugin(VolumePlugin):
    """Materialize Secret data as files (ref: pkg/volume/secret)."""
    name = "kubernetes.io/secret"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.secret is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        return _SecretBuilder(self.host.pod_volume_dir(
            pod.metadata.uid, self.name, volume.name), self, volume, pod)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self.host.pod_volume_dir(
            pod_uid, self.name, volume_name))


class _DownwardAPIBuilder(_DirBuilder):
    def __init__(self, path: str, pod: api.Pod, items=None):
        super().__init__(path)
        self.pod = pod
        self.items = items or []

    def _field_value(self, field_path: str) -> str:
        meta = self.pod.metadata
        if field_path == "metadata.name":
            return meta.name
        if field_path == "metadata.namespace":
            return meta.namespace
        if field_path == "metadata.labels":
            return json.dumps(meta.labels)
        if field_path == "metadata.annotations":
            return json.dumps(meta.annotations)
        raise ValueError(
            f"downward API: unsupported field {field_path!r} (only "
            "annotations, labels, name and namespace are supported — "
            "pkg/api/types.go:623)")

    def set_up(self) -> None:
        super().set_up()
        if self.items:
            # spec'd projection: one file per item at its relative path
            # (DownwardAPIVolumeFile, types.go:620-625)
            for item in self.items:
                rel = (item.path or "").lstrip("/")
                if not rel or ".." in rel.split("/"):
                    raise ValueError(
                        f"downward API: invalid path {item.path!r}")
                value = self._field_value(
                    item.field_ref.field_path if item.field_ref else "")
                dst = os.path.join(self.path, rel)
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                with open(dst, "w") as f:
                    f.write(value)
            return
        # no items: the standard metadata field set
        for key in ("metadata.name", "metadata.namespace",
                    "metadata.labels", "metadata.annotations"):
            with open(os.path.join(self.path, key), "w") as f:
                f.write(self._field_value(key))


class DownwardAPIPlugin(VolumePlugin):
    """Pod metadata as files (ref: pkg/volume/downwardapi)."""
    name = "kubernetes.io/downward-api"

    def can_support(self, volume: api.Volume) -> bool:
        return getattr(volume, "downward_api", None) is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        return _DownwardAPIBuilder(
            self.host.pod_volume_dir(pod.metadata.uid, self.name,
                                     volume.name),
            pod, items=volume.downward_api.items)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self.host.pod_volume_dir(
            pod_uid, self.name, volume_name))


# ---------------------------------------------------- network/cloud plugins

class _AttachingBuilder(_DirBuilder):
    """Hollow network mount: the directory is created and a `.mounted`
    marker records the source; cloud disks attach via the provider and
    detach on teardown."""

    def __init__(self, path: str, source: str, plugin: VolumePlugin,
                 attach: Optional[tuple] = None):
        super().__init__(path)
        self.source = source
        self.plugin = plugin
        self.attach = attach  # (disk_name, node) -> cloud attach call

    def set_up(self) -> None:
        cloud = getattr(self.plugin.host, "cloud", None)
        if self.attach is not None and cloud is not None:
            cloud.attach_disk(self.attach[0], self.attach[1])
        super().set_up()
        with open(os.path.join(self.path, ".mounted"), "w") as f:
            f.write(self.source)

    def tear_down(self) -> None:
        super().tear_down()
        cloud = getattr(self.plugin.host, "cloud", None)
        if self.attach is not None and cloud is not None:
            cloud.detach_disk(self.attach[0], self.attach[1])


class NFSPlugin(VolumePlugin):
    """(ref: pkg/volume/nfs — hollow mount)"""
    name = "kubernetes.io/nfs"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.nfs is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        return _AttachingBuilder(
            self.host.pod_volume_dir(pod.metadata.uid, self.name,
                                     volume.name),
            f"{volume.nfs.server}:{volume.nfs.path}", self)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self.host.pod_volume_dir(
            pod_uid, self.name, volume_name))


class GCEPDPlugin(VolumePlugin):
    """(ref: pkg/volume/gce_pd — attach via cloudprovider, hollow mount)"""
    name = "kubernetes.io/gce-pd"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.gce_persistent_disk is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        pd = volume.gce_persistent_disk
        return _AttachingBuilder(
            self.host.pod_volume_dir(pod.metadata.uid, self.name,
                                     volume.name),
            f"gce-pd://{pd.pd_name}", self,
            attach=(pd.pd_name, pod.spec.node_name))

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self.host.pod_volume_dir(
            pod_uid, self.name, volume_name))

    def new_cleaner_from_spec(self, volume: api.Volume,
                              pod: api.Pod) -> Cleaner:
        # spec-aware teardown detaches the disk too
        return self.new_builder(volume, pod)


class AWSEBSPlugin(VolumePlugin):
    """(ref: pkg/volume/aws_ebs)"""
    name = "kubernetes.io/aws-ebs"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.aws_elastic_block_store is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        ebs = volume.aws_elastic_block_store
        return _AttachingBuilder(
            self.host.pod_volume_dir(pod.metadata.uid, self.name,
                                     volume.name),
            f"aws-ebs://{ebs.volume_id}", self,
            attach=(ebs.volume_id, pod.spec.node_name))

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self.host.pod_volume_dir(
            pod_uid, self.name, volume_name))

    def new_cleaner_from_spec(self, volume: api.Volume,
                              pod: api.Pod) -> Cleaner:
        return self.new_builder(volume, pod)


# hashes, tags, branch paths — no option-looking or traversal-looking forms
_GIT_REVISION_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._/-]*")


class _GitRepoBuilder(_DirBuilder):
    """Real clone via the git binary (ref: pkg/volume/git_repo — the
    reference execs git the same way)."""

    def __init__(self, path: str, repository: str, revision: str):
        super().__init__(path)
        self.repository = repository
        self.revision = revision

    def set_up(self) -> None:
        import subprocess
        # API-supplied revision must never parse as a git option (the
        # reference hardened its git_repo volume the same way); refnames
        # and hashes never start with '-'
        if self.revision and (self.revision.startswith("-")
                              or not _GIT_REVISION_RE.fullmatch(
                                  self.revision)):
            raise BadRequest(
                f"invalid git revision {self.revision!r}")
        super().set_up()
        # resync idempotence keys on a marker written only after BOTH
        # clone and checkout succeeded — a clone whose checkout failed
        # must retry, not silently serve the default branch. The marker
        # lives inside the volume dir, so teardown removes it with it.
        marker = os.path.join(self.path, ".kubelet-git-ready")
        if os.path.exists(marker):
            return
        if os.listdir(self.path):
            shutil.rmtree(self.path)  # half-finished prior attempt
            os.makedirs(self.path)
        subprocess.run(["git", "clone", "--", self.repository, self.path],
                       check=True, capture_output=True, timeout=120)
        if self.revision:
            subprocess.run(["git", "checkout", self.revision, "--"],
                           cwd=self.path, check=True, capture_output=True,
                           timeout=60)
        with open(marker, "w"):
            pass


class GitRepoPlugin(VolumePlugin):
    """(ref: pkg/volume/git_repo)"""
    name = "kubernetes.io/git-repo"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.git_repo is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        return _GitRepoBuilder(
            self.host.pod_volume_dir(pod.metadata.uid, self.name,
                                     volume.name),
            volume.git_repo.repository, volume.git_repo.revision)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self.host.pod_volume_dir(
            pod_uid, self.name, volume_name))


class _HollowNetworkPlugin(VolumePlugin):
    """Shared shape of the network filesystems mounted hollow (the
    `.mounted` marker records the source; no cloud attach step)."""

    def _source(self, volume: api.Volume) -> str:
        raise NotImplementedError

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        return _AttachingBuilder(
            self.host.pod_volume_dir(pod.metadata.uid, self.name,
                                     volume.name),
            self._source(volume), self)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self.host.pod_volume_dir(
            pod_uid, self.name, volume_name))


class ISCSIPlugin(_HollowNetworkPlugin):
    """(ref: pkg/volume/iscsi — hollow mount)"""
    name = "kubernetes.io/iscsi"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.iscsi is not None

    def _source(self, volume: api.Volume) -> str:
        i = volume.iscsi
        return f"iscsi://{i.target_portal}/{i.iqn}/lun-{i.lun}"


class GlusterfsPlugin(_HollowNetworkPlugin):
    """(ref: pkg/volume/glusterfs — hollow mount)"""
    name = "kubernetes.io/glusterfs"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.glusterfs is not None

    def _source(self, volume: api.Volume) -> str:
        g = volume.glusterfs
        return f"glusterfs://{g.endpoints_name}/{g.path}"


class CephFSPlugin(_HollowNetworkPlugin):
    """(ref: pkg/volume/cephfs — hollow mount)"""
    name = "kubernetes.io/cephfs"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.cephfs is not None

    def _source(self, volume: api.Volume) -> str:
        c = volume.cephfs
        return f"cephfs://{','.join(c.monitors)}"


class RBDPlugin(_HollowNetworkPlugin):
    """(ref: pkg/volume/rbd — hollow mount; the disk-conflict predicate
    reads the same source fields, predicates.go:75-117)"""
    name = "kubernetes.io/rbd"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.rbd is not None

    def _source(self, volume: api.Volume) -> str:
        r = volume.rbd
        return (f"rbd://{','.join(r.ceph_monitors)}/"
                f"{r.rbd_pool}/{r.rbd_image}")


class FCPlugin(_HollowNetworkPlugin):
    """(ref: pkg/volume/fc — hollow mount of a fibre-channel LUN)"""
    name = "kubernetes.io/fc"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.fc is not None

    def _source(self, volume: api.Volume) -> str:
        f = volume.fc
        return f"fc://{','.join(f.target_wwns)}/lun-{f.lun}"


class CinderPlugin(_HollowNetworkPlugin):
    """(ref: pkg/volume/cinder — hollow mount; the OpenStack attach
    step belongs to the cloudprovider fake)"""
    name = "kubernetes.io/cinder"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.cinder is not None

    def _source(self, volume: api.Volume) -> str:
        return f"cinder://{volume.cinder.volume_id}"


class FlockerPlugin(_HollowNetworkPlugin):
    """(ref: pkg/volume/flocker — hollow mount by dataset name)"""
    name = "kubernetes.io/flocker"

    def can_support(self, volume: api.Volume) -> bool:
        return volume.flocker is not None

    def _source(self, volume: api.Volume) -> str:
        return f"flocker://{volume.flocker.dataset_name}"


class PersistentClaimPlugin(VolumePlugin):
    """Resolve claim -> bound PV -> the underlying plugin
    (ref: pkg/volume/persistent_claim)."""
    name = "kubernetes.io/persistent-claim"

    def __init__(self, mgr: "VolumePluginMgr"):
        self.mgr = mgr

    def can_support(self, volume: api.Volume) -> bool:
        return getattr(volume, "persistent_volume_claim", None) is not None

    def new_builder(self, volume: api.Volume, pod: api.Pod) -> Builder:
        client = self.host.client
        if client is None:
            raise BadRequest("persistent claims need an API client")
        claim = client.get("persistentvolumeclaims",
                           volume.persistent_volume_claim.claim_name,
                           pod.metadata.namespace)
        if not claim.spec.volume_name:
            raise BadRequest(
                f"claim {claim.metadata.name!r} is not bound yet")
        pv = client.get("persistentvolumes", claim.spec.volume_name)
        translated = _volume_from_pv(volume.name, pv)
        plugin = self.mgr.find_plugin(translated)
        return plugin.new_builder(translated, pod)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirBuilder(self.host.pod_volume_dir(
            pod_uid, self.name, volume_name))

    def new_cleaner_from_spec(self, volume: api.Volume,
                              pod: api.Pod) -> Cleaner:
        # teardown must clean what the UNDERLYING plugin set up (the
        # builder delegated; a cleaner under this plugin's own dir would
        # leak the real mount)
        client = self.host.client
        try:
            claim = client.get("persistentvolumeclaims",
                               volume.persistent_volume_claim.claim_name,
                               pod.metadata.namespace)
            pv = client.get("persistentvolumes", claim.spec.volume_name)
            translated = _volume_from_pv(volume.name, pv)
            return self.mgr.find_plugin(translated).new_cleaner_from_spec(
                translated, pod)
        except Exception:
            # claim/PV gone: fall back to this plugin's (empty) dir
            return self.new_cleaner(volume.name, pod.metadata.uid)


def _volume_from_pv(name: str, pv: api.PersistentVolume) -> api.Volume:
    if pv.spec.host_path is not None:
        return api.Volume(name=name, host_path=pv.spec.host_path)
    if pv.spec.nfs is not None:
        return api.Volume(name=name, nfs=pv.spec.nfs)
    if pv.spec.gce_persistent_disk is not None:
        return api.Volume(name=name,
                          gce_persistent_disk=pv.spec.gce_persistent_disk)
    if pv.spec.aws_elastic_block_store is not None:
        return api.Volume(
            name=name,
            aws_elastic_block_store=pv.spec.aws_elastic_block_store)
    raise BadRequest(f"PV {pv.metadata.name!r} has no supported source")


# ------------------------------------------------------------------ manager

class VolumePluginMgr:
    """(ref: plugins.go VolumePluginMgr — InitPlugins + FindPluginBySpec)"""

    def __init__(self, plugins: List[VolumePlugin], host: VolumeHost):
        self.plugins = list(plugins)
        self.host = host
        for plugin in self.plugins:
            plugin.init(host)

    def find_plugin(self, volume: api.Volume) -> VolumePlugin:
        matches = [p for p in self.plugins if p.can_support(volume)]
        if not matches:
            raise BadRequest(
                f"no volume plugin supports volume {volume.name!r}")
        if len(matches) > 1:
            raise BadRequest(
                f"multiple plugins match volume {volume.name!r}")
        return matches[0]

    def find_plugin_by_name(self, name: str) -> VolumePlugin:
        for plugin in self.plugins:
            if plugin.name == name:
                return plugin
        raise BadRequest(f"no volume plugin named {name!r}")

    def set_up_pod_volumes(self, pod: api.Pod) -> Dict[str, str]:
        """Mount every pod volume; -> volume name -> path
        (the kubelet's mountExternalVolumes role)."""
        out: Dict[str, str] = {}
        for volume in pod.spec.volumes:
            builder = self.find_plugin(volume).new_builder(volume, pod)
            builder.set_up()
            out[volume.name] = builder.get_path()
        return out

    def tear_down_pod_volumes(self, pod: api.Pod) -> None:
        for volume in pod.spec.volumes:
            plugin = self.find_plugin(volume)
            plugin.new_cleaner_from_spec(volume, pod).tear_down()

    def tear_down_orphaned(self, pod_uid: str) -> None:
        """Remove a gone pod's whole volume tree — the spec is no longer
        available, so per-plugin cleaners can't run (ref: kubelet.go
        cleanupOrphanedPodDirs)."""
        pod_dir = os.path.join(self.host.root_dir, "pods", pod_uid)
        root = os.path.realpath(self.host.root_dir)
        real = os.path.realpath(pod_dir)
        if not real.startswith(root + os.sep):
            raise BadRequest(
                f"pod dir {pod_dir!r} escapes kubelet root {root!r}")
        if os.path.isdir(real):
            shutil.rmtree(real, ignore_errors=True)


def new_default_plugin_mgr(host: VolumeHost) -> VolumePluginMgr:
    """The probed-plugin set (cmd/kubelet volume plugin registration)."""
    mgr = VolumePluginMgr([], host)
    plugins: List[VolumePlugin] = [
        EmptyDirPlugin(), HostPathPlugin(), SecretPlugin(),
        DownwardAPIPlugin(), NFSPlugin(), GCEPDPlugin(), AWSEBSPlugin(),
        GitRepoPlugin(), ISCSIPlugin(), GlusterfsPlugin(), CephFSPlugin(),
        RBDPlugin(), FCPlugin(), CinderPlugin(), FlockerPlugin(),
    ]
    claim_plugin = PersistentClaimPlugin(mgr)
    plugins.append(claim_plugin)
    for plugin in plugins:
        plugin.init(host)
    mgr.plugins = plugins
    return mgr
