"""Volume plugin framework.

Reference: pkg/volume — plugins.go (VolumePlugin interface +
VolumePluginMgr), volume.go (Builder/Cleaner), and the per-type plugins
(empty_dir, host_path, secret, downwardapi, git_repo, nfs, gce_pd,
aws_ebs, persistent_claim, ...). Local plugins (emptyDir, hostPath,
secret, downwardAPI) are functional against a real filesystem root;
network/cloud plugins (NFS, GCE PD, AWS EBS) are hollow mounts that
record attach state through the cloudprovider, the kubemark stance.
"""

from .plugins import (Builder, Cleaner, VolumeHost, VolumePlugin,
                      VolumePluginMgr, new_default_plugin_mgr)

__all__ = ["Builder", "Cleaner", "VolumeHost", "VolumePlugin",
           "VolumePluginMgr", "new_default_plugin_mgr"]
