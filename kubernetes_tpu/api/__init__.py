from .registry import Registry, ResourceInfo
from .client import Client, InProcClient, HttpClient
