"""The client-side dashboard served at /ui.

Reference: pkg/ui/installsupport.go + www/README.md — the apiserver
bundles a client-side JS application (go-bindata'd into datafile.go)
that renders cluster state by calling the public REST API from the
browser. This module plays that role at this framework's scale: ONE
static page (no server-side rendering — the shell below contains no
cluster data) whose script lists nodes/pods/events through /api/v1 and
then LIVE-UPDATES by consuming the chunked watch streams
(/api/v1/watch/..., the same NDJSON wire kubectl's --watch uses),
re-listing on stream loss exactly like a reflector (410-safe:
list -> resourceVersion -> watch).

The previous server-rendered page remains at /ui/server for
curl-style consumption; /ui itself works with the renderer gone.
"""

UI_APP_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>kubernetes_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 1.5em; }
 h1 { margin-bottom: 0.2em; }
 #status { color: #666; margin-bottom: 1em; }
 #status .live { color: #0a0; font-weight: bold; }
 #status .down { color: #a00; font-weight: bold; }
 .counts span { margin-right: 1.5em; }
 table { border-collapse: collapse; margin-bottom: 1em; }
 td, th { border: 1px solid #ccc; padding: 3px 10px;
          font-size: 13px; text-align: left; }
 th { background: #f5f5f5; }
 input, select { margin: 0 0.8em 0.6em 0; padding: 2px 6px; }
 .trunc { color: #888; font-size: 12px; }
</style>
</head>
<body>
<h1>kubernetes_tpu</h1>
<div id="status">connecting&hellip;
 (<a href="/swaggerapi">swagger</a>, <a href="/metrics">metrics</a>,
  <a href="/healthz">healthz</a>, <a href="/ui/server">server-rendered</a>)
</div>
<div class="counts" id="counts"></div>
<h2>Pods</h2>
<input id="podFilter" placeholder="filter name/node" />
<select id="phaseFilter"><option value="">all phases</option></select>
<div id="pods"></div>
<h2>Nodes</h2>
<input id="nodeFilter" placeholder="filter name" />
<div id="nodes"></div>
<h2>Events</h2>
<div id="events"></div>
<script>
"use strict";
const MAX_ROWS = 500;
const state = {
  pods: new Map(), nodes: new Map(), events: [],
  streams: {pods: false, nodes: false, events: false},
};
const esc = s => String(s == null ? "" : s)
  .replace(/&/g, "&amp;").replace(/</g, "&lt;").replace(/>/g, "&gt;");
const key = o => (o.metadata.namespace || "") + "/" + o.metadata.name;

function nodeReady(n) {
  for (const c of (n.status && n.status.conditions) || [])
    if (c.type === "Ready") return c.status === "True" ? "Ready" : "NotReady";
  return "Unknown";
}

let renderQueued = false;
function queueRender() {      // coalesce bursts (a 30k-pod bind storm)
  if (renderQueued) return;
  renderQueued = true;
  setTimeout(() => { renderQueued = false; render(); }, 250);
}

function renderTable(el, header, rows, total) {
  let html = "<table><tr>" +
    header.map(h => "<th>" + esc(h) + "</th>").join("") + "</tr>";
  for (const r of rows.slice(0, MAX_ROWS))
    html += "<tr>" + r.map(c => "<td>" + esc(c) + "</td>").join("") + "</tr>";
  html += "</table>";
  if (total > MAX_ROWS)
    html += '<div class="trunc">showing ' + MAX_ROWS + " of " +
            total + "</div>";
  el.innerHTML = html;
}

function render() {
  const phases = {};
  let bound = 0;
  for (const p of state.pods.values()) {
    const ph = (p.status && p.status.phase) || "Unknown";
    phases[ph] = (phases[ph] || 0) + 1;
    if (p.spec && p.spec.nodeName) bound++;
  }
  let ready = 0;
  for (const n of state.nodes.values())
    if (nodeReady(n) === "Ready") ready++;
  document.getElementById("counts").innerHTML =
    "<span>nodes: <b>" + ready + "/" + state.nodes.size +
    "</b> ready</span><span>pods: <b>" + state.pods.size +
    "</b> (" + bound + " bound; " +
    esc(Object.entries(phases).map(([k, v]) => k + ": " + v)
        .join(", ") || "none") + ")</span>";

  const phaseSel = document.getElementById("phaseFilter");
  const have = new Set([...phaseSel.options].map(o => o.value));
  for (const ph of Object.keys(phases))
    if (!have.has(ph)) phaseSel.add(new Option(ph, ph));

  const pf = document.getElementById("podFilter").value.toLowerCase();
  const phf = phaseSel.value;
  const podRows = [];
  let podTotal = 0;
  for (const p of state.pods.values()) {
    const ph = (p.status && p.status.phase) || "Unknown";
    const node = (p.spec && p.spec.nodeName) || "";
    if (phf && ph !== phf) continue;
    if (pf && !(key(p).toLowerCase().includes(pf) ||
                node.toLowerCase().includes(pf))) continue;
    podTotal++;
    if (podRows.length < MAX_ROWS)
      podRows.push([p.metadata.namespace, p.metadata.name, ph,
                    node || "\\u2014"]);
  }
  renderTable(document.getElementById("pods"),
              ["namespace", "name", "phase", "node"], podRows, podTotal);

  const nf = document.getElementById("nodeFilter").value.toLowerCase();
  const nodeRows = [];
  let nodeTotal = 0;
  for (const n of state.nodes.values()) {
    if (nf && !n.metadata.name.toLowerCase().includes(nf)) continue;
    nodeTotal++;
    if (nodeRows.length < MAX_ROWS) {
      // n.status itself may be absent: the wire encoder omits empty
      // fields, and a node can list before its first status write
      const cap = (n.status && n.status.capacity) || {};
      nodeRows.push([n.metadata.name, nodeReady(n),
                     cap.cpu || "", cap.memory || ""]);
    }
  }
  renderTable(document.getElementById("nodes"),
              ["name", "status", "cpu", "memory"], nodeRows, nodeTotal);

  const evRows = state.events.slice(-30).reverse().map(e => [
    e.type, e.reason,
    (e.involvedObject || {}).kind + "/" + (e.involvedObject || {}).name,
    e.message, e.count]);
  renderTable(document.getElementById("events"),
              ["type", "reason", "object", "message", "count"],
              evRows, evRows.length);
  renderStatus();
}

function renderStatus() {
  const live = Object.values(state.streams).every(v => v);
  document.getElementById("status").innerHTML =
    (live ? '<span class="live">&#9679; live</span> watching ' +
            "pods/nodes/events"
          : '<span class="down">&#9679; reconnecting&hellip;</span>') +
    ' (<a href="/swaggerapi">swagger</a>,' +
    ' <a href="/metrics">metrics</a>,' +
    ' <a href="/healthz">healthz</a>,' +
    ' <a href="/ui/server">server-rendered</a>)';
}

function apply(kind, ev) {
  if (kind === "events") {
    if (ev.type !== "DELETED") state.events.push(ev.object);
    if (state.events.length > 200) state.events.splice(0, 100);
    return;
  }
  const m = state[kind];
  if (ev.type === "DELETED") m.delete(key(ev.object));
  else m.set(key(ev.object), ev.object);
}

async function reflect(kind, resource) {
  // a reflector in the browser: LIST for a resourceVersion, then
  // consume the chunked watch; any failure (incl. 410 Expired) falls
  // back to a fresh LIST
  for (;;) {
    let rv;
    try {
      const resp = await fetch("/api/v1/" + resource);
      const body = await resp.json();
      rv = (body.metadata || {}).resourceVersion || "";
      if (kind === "events") state.events = body.items || [];
      else {
        state[kind] = new Map(
          (body.items || []).map(o => [key(o), o]));
      }
      queueRender();
      const watch = await fetch("/api/v1/watch/" + resource +
                                "?resourceVersion=" + rv);
      if (!watch.ok || !watch.body) throw new Error("watch " + watch.status);
      state.streams[kind] = true;
      renderStatus();
      const reader = watch.body.getReader();
      const dec = new TextDecoder();
      let buf = "";
      for (;;) {
        const {done, value} = await reader.read();
        if (done) break;
        buf += dec.decode(value, {stream: true});
        let nl;
        while ((nl = buf.indexOf("\\n")) >= 0) {
          const line = buf.slice(0, nl).trim();
          buf = buf.slice(nl + 1);
          if (!line) continue;          // keep-alive blank
          apply(kind, JSON.parse(line));
          queueRender();
        }
      }
    } catch (e) { /* fall through to re-list */ }
    state.streams[kind] = false;
    renderStatus();
    await new Promise(r => setTimeout(r, 1000));
  }
}

for (const id of ["podFilter", "phaseFilter", "nodeFilter"])
  document.getElementById(id).addEventListener("input", queueRender);
reflect("pods", "pods");
reflect("nodes", "nodes");
reflect("events", "events");
</script>
</body>
</html>
"""
