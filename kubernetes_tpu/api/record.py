"""Event recording: broadcaster, recorder, and correlation (dedup/aggregation).

Reference: pkg/client/record/event.go (EventBroadcaster :80-105, recordToSink
retry loop :105-160) and pkg/client/record/events_cache.go (EventAggregator
:69-92, eventLogger dedup). Behavior kept:

- Events are fire-and-forget from the caller's perspective; a broadcaster
  fans them out to sinks on background threads.
- Aggregation: events identical except for message, seen more than
  ``aggregate_max_events`` (10) times inside ``aggregate_interval`` (600s),
  collapse into one event whose message is the aggregate marker
  (events_cache.go:99 EventAggregatorByReasonMessageFunc).
- Dedup: an event with an already-seen key increments ``count`` and bumps
  ``last_timestamp`` on the server copy instead of creating a new object
  (events_cache.go eventObserve / the update branch of recordToSink).
- Sink errors retry up to ``max_tries`` with a sleep between tries
  (event.go:105-130, maxTriesPerEvent=12); we keep the structure with a
  smaller default so tests stay fast.
"""

from __future__ import annotations

import itertools
import queue
import threading
from collections import OrderedDict
from dataclasses import replace
from typing import Callable, List, Optional, Tuple

from ..core import types as api
from ..core.errors import AlreadyExists
from ..utils.clock import Clock, RealClock

MAX_LRU_CACHE_ENTRIES = 4096  # events_cache.go:37
DEFAULT_AGGREGATE_MAX_EVENTS = 10  # events_cache.go:41
DEFAULT_AGGREGATE_INTERVAL_SECONDS = 600  # events_cache.go:42


def _ref_key(ref: api.ObjectReference) -> str:
    return "".join([ref.kind, ref.namespace, ref.name, ref.uid,
                    ref.api_version])


def get_event_key(event: api.Event) -> str:
    """Full dedup key incl. message (events_cache.go:46 getEventKey)."""
    return "".join([event.source.component, event.source.host,
                    _ref_key(event.involved_object), event.type,
                    event.reason, event.message])


def aggregate_key(event: api.Event) -> Tuple[str, str]:
    """(group key w/o message, local key = message)
    (events_cache.go:77 EventAggregatorByReasonFunc)."""
    return ("".join([event.source.component, event.source.host,
                     _ref_key(event.involved_object), event.type,
                     event.reason]),
            event.message)


class _LRU:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            if key not in self._d:
                return None
            self._d.move_to_end(key)
            return self._d[key]

    def put(self, key, value):
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)


class EventAggregator:
    """Collapses event floods that differ only in message
    (events_cache.go:103 EventAggregator.EventAggregate)."""

    def __init__(self, clock: Clock,
                 max_events: int = DEFAULT_AGGREGATE_MAX_EVENTS,
                 max_interval: float = DEFAULT_AGGREGATE_INTERVAL_SECONDS,
                 capacity: int = MAX_LRU_CACHE_ENTRIES):
        self.clock = clock
        self.max_events = max_events
        self.max_interval = max_interval
        self._cache = _LRU(capacity)

    def aggregate(self, event: api.Event) -> api.Event:
        group, local = aggregate_key(event)
        now = self.clock.now()
        record = self._cache.get(group)
        if record is None or now - record["last"] > self.max_interval:
            record = {"keys": set(), "last": now}
        record["keys"].add(local)
        record["last"] = now
        self._cache.put(group, record)
        if len(record["keys"]) < self.max_events:
            return event
        # similar-but-distinct flood: collapse message
        return replace(event,
                       message="(events with common reason combined)")


class EventLogger:
    """Observed-event state: returns (event, is_update) where an update
    carries the accumulated count / first_timestamp
    (events_cache.go eventLogger.eventObserve)."""

    def __init__(self, capacity: int = MAX_LRU_CACHE_ENTRIES):
        self._cache = _LRU(capacity)

    def observe(self, event: api.Event) -> Tuple[api.Event, bool]:
        key = get_event_key(event)
        prior = self._cache.get(key)
        if prior is not None:
            event = replace(
                event,
                metadata=replace(event.metadata,
                                 name=prior["name"],
                                 resource_version=prior["resource_version"]),
                first_timestamp=prior["first_timestamp"],
                count=prior["count"] + 1)
            self._cache.put(key, self._state(event))
            return event, True
        self._cache.put(key, self._state(event))
        return event, False

    def update_state(self, event: api.Event) -> None:
        """Record the server-assigned name/resourceVersion after a write
        (event.go updates the cache from the sink response)."""
        self._cache.put(get_event_key(event), self._state(event))

    @staticmethod
    def _state(event: api.Event) -> dict:
        return {"name": event.metadata.name,
                "resource_version": event.metadata.resource_version,
                "first_timestamp": event.first_timestamp,
                "count": event.count}


class EventCorrelator:
    """filter -> aggregate -> dedup pipeline
    (events_cache.go EventCorrelator)."""

    def __init__(self, clock: Clock,
                 filter_func: Optional[Callable[[api.Event], bool]] = None):
        self.filter_func = filter_func or (lambda e: False)
        self.aggregator = EventAggregator(clock)
        self.logger = EventLogger()

    def correlate(self, event: api.Event) -> Tuple[Optional[api.Event], bool]:
        if self.filter_func(event):
            return None, False
        return self.logger.observe(self.aggregator.aggregate(event))


class EventSink:
    """Where correlated events land (event.go EventSink: Create/Update)."""

    def create(self, event: api.Event) -> api.Event:
        raise NotImplementedError

    def update(self, event: api.Event) -> api.Event:
        raise NotImplementedError


class ClientEventSink(EventSink):
    def __init__(self, client):
        self.client = client

    def create(self, event):
        return self.client.create("events", event,
                                  event.metadata.namespace or "default")

    def update(self, event):
        return self.client.update("events", event,
                                  event.metadata.namespace or "default")


class EventBroadcaster:
    """Fan events out to sinks + local watchers
    (event.go:80 NewBroadcaster over watch.Broadcaster)."""

    def __init__(self, clock: Optional[Clock] = None,
                 max_tries: int = 3, sleep_between_tries: float = 1.0,
                 queue_size: int = 1000):
        self.clock = clock or RealClock()
        self.max_tries = max_tries
        self.sleep_between_tries = sleep_between_tries
        self.queue_size = queue_size
        # one queue per sink so every sink sees every event
        self._queues: List["queue.Queue"] = []
        self._watchers: List[Callable[[api.Event], None]] = []
        self._threads: List[threading.Thread] = []
        self._stopped = threading.Event()

    # -- recording side ---------------------------------------------------

    def new_recorder(self, source: api.EventSource) -> "EventRecorder":
        return EventRecorder(self, source, self.clock)

    def _publish(self, event: api.Event) -> None:
        for fn in list(self._watchers):
            try:
                fn(event)
            except Exception:
                pass
        for q in list(self._queues):
            try:
                q.put_nowait(event)
            except queue.Full:  # drop, don't block the caller (event.go mux)
                pass

    # -- consuming side ---------------------------------------------------

    def start_event_watcher(self,
                            fn: Callable[[api.Event], None]) -> None:
        self._watchers.append(fn)

    def start_recording_to_sink(self, sink: EventSink) -> "EventBroadcaster":
        correlator = EventCorrelator(self.clock)
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        self._queues.append(q)
        t = threading.Thread(target=self._drain, args=(q, sink, correlator),
                             daemon=True, name="event-broadcaster")
        t.start()
        self._threads.append(t)
        return self

    def _drain(self, q: "queue.Queue", sink: EventSink,
               correlator: EventCorrelator) -> None:
        while True:
            event = q.get()
            if event is None or self._stopped.is_set():
                return
            self._record_one(sink, correlator, event)

    def _record_one(self, sink: EventSink, correlator: EventCorrelator,
                    event: api.Event) -> None:
        correlated, is_update = correlator.correlate(event)
        if correlated is None:
            return
        for attempt in range(self.max_tries):
            try:
                if is_update and correlated.metadata.resource_version:
                    try:
                        written = sink.update(correlated)
                    except AlreadyExists:
                        raise  # let the outer replay guard settle it
                    except Exception:
                        # server copy expired (events have a TTL) or CAS
                        # conflict: fall back to create with a cleared
                        # resourceVersion (event.go recordEvent NotFound path)
                        correlated = replace(
                            correlated,
                            metadata=replace(correlated.metadata,
                                             resource_version=""))
                        written = sink.create(correlated)
                else:
                    written = sink.create(correlated)
                correlator.logger.update_state(written)
                return
            except AlreadyExists:
                # event names are unique per occurrence, so the only
                # way the name exists is that an earlier attempt's
                # create committed and the response was lost — the
                # event is recorded; replaying would duplicate it
                return
            except Exception:
                if attempt + 1 >= self.max_tries:
                    return
                self.clock.sleep(self.sleep_between_tries)

    def flush(self, timeout: float = 5.0) -> None:
        """Wait for the queues to drain (tests)."""
        deadline = self.clock.now() + timeout
        while (any(not q.empty() for q in self._queues)
               and self.clock.now() < deadline):
            self.clock.sleep(0.01)

    def shutdown(self) -> None:
        self._stopped.set()
        for q in list(self._queues):
            try:
                q.put_nowait(None)
            except queue.Full:
                pass


class EventRecorder:
    """(event.go recorderImpl.Event/Eventf)"""

    _seq = itertools.count()  # disambiguates same-instant events

    def __init__(self, broadcaster: EventBroadcaster,
                 source: api.EventSource, clock: Clock):
        self._broadcaster = broadcaster
        self.source = source
        self.clock = clock

    def event(self, obj, event_type: str, reason: str,
              message: str) -> None:
        ref = object_reference(obj)
        ts = api.now_rfc3339()
        self._broadcaster._publish(api.Event(
            metadata=api.ObjectMeta(
                # name pattern: <involved>.<unique> (event.go makeEvent);
                # a process-wide counter keeps names unique within one
                # clock tick (coarse clocks / FakeClock)
                name=(f"{ref.name}.{int(self.clock.now() * 1e9):x}"
                      f".{next(self._seq):x}"),
                namespace=ref.namespace or "default"),
            involved_object=ref,
            reason=reason, message=message,
            source=self.source,
            first_timestamp=ts, last_timestamp=ts,
            count=1, type=event_type))

    def eventf(self, obj, event_type: str, reason: str,
               fmt: str, *args) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)


class FakeRecorder:
    """(event.go FakeRecorder) — collects 'Reason Message' strings."""

    def __init__(self):
        self.events: List[str] = []

    def event(self, obj, event_type, reason, message):
        self.events.append(f"{event_type} {reason} {message}")

    def eventf(self, obj, event_type, reason, fmt, *args):
        self.event(obj, event_type, reason, fmt % args if args else fmt)


def object_reference(obj) -> api.ObjectReference:
    """(pkg/api/ref.go GetReference, simplified: our objects always carry
    kind via type name)"""
    if isinstance(obj, api.ObjectReference):
        return obj
    meta = getattr(obj, "metadata", None) or api.ObjectMeta()
    return api.ObjectReference(
        kind=type(obj).__name__, namespace=meta.namespace,
        name=meta.name, uid=meta.uid, api_version="v1")
