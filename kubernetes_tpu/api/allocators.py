"""Service cluster-IP and node-port allocators.

Reference: pkg/registry/service with pkg/registry/service/ipallocator
(bitmap over the service CIDR, network/broadcast excluded) and
portallocator (the node-port range, default 30000-32767). The service
REST strategy allocates on create, honors explicit requests, rejects
collisions, and releases on delete.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Optional, Set

from ..core.errors import Invalid


class AllocationError(Invalid):
    pass


class IPAllocator:
    """(ref: ipallocator.Range)"""

    def __init__(self, cidr: str = "10.0.0.0/24"):
        self.network = ipaddress.ip_network(cidr)
        self._base = int(self.network.network_address)
        # usable host addresses: skip network and broadcast
        self._size = self.network.num_addresses - 2
        if self._size <= 0:
            raise AllocationError(f"service CIDR {cidr} has no usable IPs")
        self._used: Set[int] = set()
        self._next = 0
        self._lock = threading.Lock()

    def allocate(self) -> str:
        with self._lock:
            for probe in range(self._size):
                offset = (self._next + probe) % self._size
                if offset not in self._used:
                    self._used.add(offset)
                    self._next = (offset + 1) % self._size
                    return str(ipaddress.ip_address(
                        self._base + 1 + offset))
            raise AllocationError(
                f"service CIDR {self.network} is exhausted")

    def allocate_specific(self, ip: str) -> str:
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            raise AllocationError(f"invalid IP address {ip!r}")
        if addr not in self.network:
            raise AllocationError(
                f"IP {ip} is not in the service CIDR {self.network}")
        offset = int(addr) - self._base - 1
        if offset < 0 or offset >= self._size:
            raise AllocationError(f"IP {ip} is reserved")
        with self._lock:
            if offset in self._used:
                raise AllocationError(f"IP {ip} is already allocated")
            self._used.add(offset)
        return ip

    def release(self, ip: str) -> None:
        try:
            offset = int(ipaddress.ip_address(ip)) - self._base - 1
        except ValueError:
            return
        with self._lock:
            self._used.discard(offset)

    def has(self, ip: str) -> bool:
        try:
            offset = int(ipaddress.ip_address(ip)) - self._base - 1
        except ValueError:
            return False
        with self._lock:
            return offset in self._used


class PortAllocator:
    """(ref: service/portallocator.PortAllocator; default range
    --service-node-port-range=30000-32767)"""

    def __init__(self, base: int = 30000, size: int = 2768):
        self.base = base
        self.size = size
        self._used: Set[int] = set()
        self._next = 0
        self._lock = threading.Lock()

    def allocate(self) -> int:
        with self._lock:
            for probe in range(self.size):
                offset = (self._next + probe) % self.size
                if offset not in self._used:
                    self._used.add(offset)
                    self._next = (offset + 1) % self.size
                    return self.base + offset
            raise AllocationError("node-port range is exhausted")

    def allocate_specific(self, port: int) -> int:
        offset = port - self.base
        if offset < 0 or offset >= self.size:
            raise AllocationError(
                f"port {port} is outside the node-port range "
                f"{self.base}-{self.base + self.size - 1}")
        with self._lock:
            if offset in self._used:
                raise AllocationError(f"port {port} is already allocated")
            self._used.add(offset)
        return port

    def release(self, port: int) -> None:
        with self._lock:
            self._used.discard(port - self.base)
