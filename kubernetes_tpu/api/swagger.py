"""Swagger API discovery + the minimal UI page.

Reference: pkg/apiserver InstallSwaggerAPI (go-restful swagger at
/swaggerapi) and pkg/ui (the bundled dashboard at /ui; its 17k LoC of
go-bindata'd JS is replaced by one reflective page — the reference's
generated datafile.go is exactly the kind of artifact this design
obviates). Models are derived from the dataclass schema the same way
the serde is, so the docs can never drift from the wire format.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, get_args, get_origin

from ..core.quantity import Quantity
from ..core.serde import _camel
from .registry import RESOURCES


def _type_name(tp: Any) -> str:
    tp_origin = get_origin(tp)
    if tp_origin is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        return _type_name(args[0]) if args else "any"
    if tp_origin in (list, tuple):
        (elem,) = get_args(tp) or (Any,)
        return f"array[{_type_name(elem)}]"
    if tp_origin is dict:
        args = get_args(tp)
        vtp = args[1] if len(args) == 2 else Any
        return f"map[string,{_type_name(vtp)}]"
    if tp is Quantity:
        return "string"
    if dataclasses.is_dataclass(tp):
        return tp.__name__
    return getattr(tp, "__name__", "any")


def _collect_models(cls: type, models: Dict[str, dict]) -> None:
    if not dataclasses.is_dataclass(cls) or cls.__name__ in models:
        return
    props: Dict[str, dict] = {}
    models[cls.__name__] = {"id": cls.__name__, "properties": props}
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        tp = hints[f.name]
        props[_camel(f.name)] = {"type": _type_name(tp)}
        # recurse into nested dataclasses (incl. through containers)
        stack = [tp]
        while stack:
            t = stack.pop()
            origin = get_origin(t)
            if origin is not None:
                stack.extend(get_args(t))
            elif dataclasses.is_dataclass(t):
                _collect_models(t, models)


def swagger_api(base_url: str = "") -> dict:
    """The /swaggerapi document: one api entry per REST resource plus
    the reflected model schemas."""
    apis = []
    models: Dict[str, dict] = {}
    for name, info in sorted(RESOURCES.items()):
        prefix = ("/apis/extensions/v1beta1" if _is_extensions(name)
                  else "/api/v1")
        path = (f"{prefix}/namespaces/{{namespace}}/{name}"
                if info.namespaced else f"{prefix}/{name}")
        apis.append({
            "path": path,
            "description": f"API for {info.kind} ({name})",
            "operations": [
                {"method": m, "type": info.kind}
                for m in ("GET", "POST", "PUT", "DELETE")],
        })
        _collect_models(info.cls, models)
    return {
        "swaggerVersion": "1.2",
        "basePath": base_url,
        "apiVersion": "v1",
        "apis": apis,
        "models": models,
    }


def _is_extensions(resource: str) -> bool:
    from .registry import EXTENSIONS_RESOURCES
    return resource in EXTENSIONS_RESOURCES


def _esc(s: Any) -> str:
    return (str(s).replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


def _node_ready(node) -> str:
    for c in node.status.conditions:
        if c.type == "Ready":
            return "Ready" if c.status == "True" else "NotReady"
    return "Unknown"


def ui_page(registry=None, namespace: str = "", limit: int = 500) -> str:
    """The /ui dashboard (pkg/ui's role): a live server-rendered view of
    nodes, pods (phase/host), and recent events straight from the watch
    cache (the in-process store IS the cache), refreshing every 5s —
    the reference's 17k-LoC go-bindata'd JS dashboard replaced by one
    reflective page over the same data."""
    index_rows = "\n".join(
        f'<tr><td><a href="{_href(name, info)}">{name}</a></td>'
        f"<td>{info.kind}</td>"
        f"<td>{'namespaced' if info.namespaced else 'cluster'}</td></tr>"
        for name, info in sorted(RESOURCES.items()))
    cluster = ""
    if registry is not None:
        nodes, _ = registry.list("nodes", "")
        pods, _ = registry.list("pods", namespace)
        events, _ = registry.list("events", namespace)
        phases: Dict[str, int] = {}
        for p in pods:
            phases[p.status.phase or "Unknown"] = \
                phases.get(p.status.phase or "Unknown", 0) + 1
        ready = sum(1 for n in nodes if _node_ready(n) == "Ready")
        phase_sum = ", ".join(f"{_esc(k)}: {v}"
                              for k, v in sorted(phases.items()))
        pods_by_node: Dict[str, int] = {}
        for p in pods:
            if p.spec.node_name:
                pods_by_node[p.spec.node_name] = \
                    pods_by_node.get(p.spec.node_name, 0) + 1
        node_rows = "\n".join(
            f"<tr><td>{_esc(n.metadata.name)}</td>"
            f"<td>{_node_ready(n)}</td>"
            f"<td>{_esc(n.status.capacity.get('cpu', ''))}</td>"
            f"<td>{_esc(n.status.capacity.get('memory', ''))}</td>"
            f"<td>{pods_by_node.get(n.metadata.name, 0)}</td></tr>"
            for n in nodes[:limit])
        pod_rows = "\n".join(
            f"<tr><td>{_esc(p.metadata.namespace)}</td>"
            f"<td>{_esc(p.metadata.name)}</td>"
            f"<td>{_esc(p.status.phase)}</td>"
            f"<td>{_esc(p.spec.node_name) if p.spec.node_name else '&mdash;'}"
            f"</td></tr>"
            for p in pods[:limit])
        recent = sorted(events, key=lambda e: e.last_timestamp or "",
                        reverse=True)[:30]
        event_rows = "\n".join(
            f"<tr><td>{_esc(e.type)}</td><td>{_esc(e.reason)}</td>"
            f"<td>{_esc(e.involved_object.kind)}/"
            f"{_esc(e.involved_object.name)}</td>"
            f"<td>{_esc(e.message)}</td><td>{e.count}</td></tr>"
            for e in recent)
        trunc_pods = (f"<p>showing {limit} of {len(pods)} pods</p>"
                      if len(pods) > limit else "")
        trunc_nodes = (f"<p>showing {limit} of {len(nodes)} nodes</p>"
                       if len(nodes) > limit else "")
        cluster = f"""
<h2>Cluster</h2>
<p>nodes: {ready}/{len(nodes)} ready &middot; pods: {len(pods)}
 ({phase_sum or "none"})</p>
<h2>Nodes</h2>
<table><tr><th>name</th><th>status</th><th>cpu</th><th>memory</th>
<th>pods</th></tr>
{node_rows}
</table>{trunc_nodes}
<h2>Pods</h2>
<table><tr><th>namespace</th><th>name</th><th>phase</th><th>node</th></tr>
{pod_rows}
</table>{trunc_pods}
<h2>Recent events</h2>
<table><tr><th>type</th><th>reason</th><th>object</th><th>message</th>
<th>count</th></tr>
{event_rows}
</table>"""
    return f"""<!DOCTYPE html>
<html><head><title>kubernetes_tpu</title>
<meta http-equiv="refresh" content="5">
<style>
 body {{ font-family: sans-serif; margin: 2em; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #ccc; padding: 4px 12px; }}
 h2 {{ margin-top: 1.2em; }}
</style></head>
<body>
<h1>kubernetes_tpu</h1>
<p>(<a href="/swaggerapi">swagger</a>,
<a href="/metrics">metrics</a>, <a href="/healthz">healthz</a>)</p>
{cluster}
<h2>API resources</h2>
<table><tr><th>resource</th><th>kind</th><th>scope</th></tr>
{index_rows}
</table></body></html>"""


def _href(name: str, info) -> str:
    prefix = ("/apis/extensions/v1beta1" if _is_extensions(name)
              else "/api/v1")
    return f"{prefix}/{name}"
