"""Swagger API discovery + the minimal UI page.

Reference: pkg/apiserver InstallSwaggerAPI (go-restful swagger at
/swaggerapi) and pkg/ui (the bundled dashboard at /ui; its 17k LoC of
go-bindata'd JS is replaced by one reflective page — the reference's
generated datafile.go is exactly the kind of artifact this design
obviates). Models are derived from the dataclass schema the same way
the serde is, so the docs can never drift from the wire format.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, get_args, get_origin

from ..core.quantity import Quantity
from ..core.serde import _camel
from .registry import RESOURCES


def _type_name(tp: Any) -> str:
    tp_origin = get_origin(tp)
    if tp_origin is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        return _type_name(args[0]) if args else "any"
    if tp_origin in (list, tuple):
        (elem,) = get_args(tp) or (Any,)
        return f"array[{_type_name(elem)}]"
    if tp_origin is dict:
        args = get_args(tp)
        vtp = args[1] if len(args) == 2 else Any
        return f"map[string,{_type_name(vtp)}]"
    if tp is Quantity:
        return "string"
    if dataclasses.is_dataclass(tp):
        return tp.__name__
    return getattr(tp, "__name__", "any")


def _collect_models(cls: type, models: Dict[str, dict]) -> None:
    if not dataclasses.is_dataclass(cls) or cls.__name__ in models:
        return
    props: Dict[str, dict] = {}
    models[cls.__name__] = {"id": cls.__name__, "properties": props}
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        tp = hints[f.name]
        props[_camel(f.name)] = {"type": _type_name(tp)}
        # recurse into nested dataclasses (incl. through containers)
        stack = [tp]
        while stack:
            t = stack.pop()
            origin = get_origin(t)
            if origin is not None:
                stack.extend(get_args(t))
            elif dataclasses.is_dataclass(t):
                _collect_models(t, models)


def swagger_api(base_url: str = "") -> dict:
    """The /swaggerapi document: one api entry per REST resource plus
    the reflected model schemas."""
    apis = []
    models: Dict[str, dict] = {}
    for name, info in sorted(RESOURCES.items()):
        prefix = ("/apis/extensions/v1beta1" if _is_extensions(name)
                  else "/api/v1")
        path = (f"{prefix}/namespaces/{{namespace}}/{name}"
                if info.namespaced else f"{prefix}/{name}")
        apis.append({
            "path": path,
            "description": f"API for {info.kind} ({name})",
            "operations": [
                {"method": m, "type": info.kind}
                for m in ("GET", "POST", "PUT", "DELETE")],
        })
        _collect_models(info.cls, models)
    return {
        "swaggerVersion": "1.2",
        "basePath": base_url,
        "apiVersion": "v1",
        "apis": apis,
        "models": models,
    }


def _is_extensions(resource: str) -> bool:
    from .registry import EXTENSIONS_RESOURCES
    return resource in EXTENSIONS_RESOURCES


def ui_page() -> str:
    """The /ui dashboard: live resource listing (pkg/ui's role)."""
    rows = "\n".join(
        f'<tr><td><a href="{_href(name, info)}">{name}</a></td>'
        f"<td>{info.kind}</td>"
        f"<td>{'namespaced' if info.namespaced else 'cluster'}</td></tr>"
        for name, info in sorted(RESOURCES.items()))
    return f"""<!DOCTYPE html>
<html><head><title>kubernetes_tpu</title>
<style>
 body {{ font-family: sans-serif; margin: 2em; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #ccc; padding: 4px 12px; }}
</style></head>
<body>
<h1>kubernetes_tpu</h1>
<p>API resources (<a href="/swaggerapi">swagger</a>,
<a href="/metrics">metrics</a>, <a href="/healthz">healthz</a>)</p>
<table><tr><th>resource</th><th>kind</th><th>scope</th></tr>
{rows}
</table></body></html>"""


def _href(name: str, info) -> str:
    prefix = ("/apis/extensions/v1beta1" if _is_extensions(name)
              else "/api/v1")
    return f"{prefix}/{name}"
