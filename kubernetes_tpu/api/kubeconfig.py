"""kubeconfig loading — the clientcmd role.

Reference: pkg/client/unversioned/clientcmd (kubeconfig schema: clusters
/ users / contexts / current-context, merged from --kubeconfig, the
KUBECONFIG env var, or ~/.kube/config) feeding client.Config. Supports
the credential forms the server side understands: bearer token,
token-file, and basic auth.
"""

from __future__ import annotations

import base64
import os
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..core.errors import BadRequest

DEFAULT_PATH = os.path.join(os.path.expanduser("~"), ".kube", "config")


@dataclass
class Cluster:
    server: str = ""


@dataclass
class AuthInfo:
    token: str = ""
    token_file: str = ""
    username: str = ""
    password: str = ""


@dataclass
class Context:
    cluster: str = ""
    user: str = ""
    namespace: str = ""


@dataclass
class KubeConfig:
    clusters: Dict[str, Cluster] = field(default_factory=dict)
    users: Dict[str, AuthInfo] = field(default_factory=dict)
    contexts: Dict[str, Context] = field(default_factory=dict)
    current_context: str = ""

    def resolve(self, context: str = ""):
        """-> (server, headers, namespace) for a context (default: the
        current-context), ready for HttpClient."""
        name = context or self.current_context
        if not name:
            raise BadRequest("kubeconfig has no current-context")
        ctx = self.contexts.get(name)
        if ctx is None:
            raise BadRequest(f"context {name!r} not found in kubeconfig")
        cluster = self.clusters.get(ctx.cluster)
        if cluster is None or not cluster.server:
            raise BadRequest(
                f"context {name!r} names unknown cluster {ctx.cluster!r}")
        headers: Dict[str, str] = {}
        user = self.users.get(ctx.user)
        if user is not None:
            token = user.token
            if not token and user.token_file:
                with open(user.token_file) as f:
                    token = f.read().strip()
            if token:
                headers["Authorization"] = f"Bearer {token}"
            elif user.username:
                raw = f"{user.username}:{user.password}".encode()
                headers["Authorization"] = \
                    "Basic " + base64.b64encode(raw).decode()
        return cluster.server, headers, ctx.namespace or "default"


def load_kubeconfig(path: Optional[str] = None) -> KubeConfig:
    """Load one kubeconfig file (YAML or JSON — YAML is a superset).
    Resolution order mirrors clientcmd: explicit path, $KUBECONFIG,
    ~/.kube/config."""
    try:
        import yaml
        loads = yaml.safe_load
    except ImportError:  # stdlib-only environments: JSON configs work
        import json
        loads = json.loads

    path = path or os.environ.get("KUBECONFIG") or DEFAULT_PATH
    with open(path) as f:
        data = loads(f.read()) or {}
    cfg = KubeConfig(current_context=data.get("current-context", ""))
    for entry in data.get("clusters", []):
        cfg.clusters[entry.get("name", "")] = Cluster(
            server=(entry.get("cluster") or {}).get("server", ""))
    for entry in data.get("users", []):
        u = entry.get("user") or {}
        cfg.users[entry.get("name", "")] = AuthInfo(
            token=u.get("token", ""),
            token_file=u.get("tokenFile", ""),
            username=u.get("username", ""),
            password=u.get("password", ""))
    for entry in data.get("contexts", []):
        c = entry.get("context") or {}
        cfg.contexts[entry.get("name", "")] = Context(
            cluster=c.get("cluster", ""), user=c.get("user", ""),
            namespace=c.get("namespace", ""))
    return cfg


def dump_kubeconfig(cfg: KubeConfig) -> dict:
    """KubeConfig -> the on-disk wire shape (clientcmd's v1 Config)."""
    return {
        "apiVersion": "v1", "kind": "Config",
        "current-context": cfg.current_context,
        "clusters": [{"name": name,
                      "cluster": {"server": c.server}}
                     for name, c in sorted(cfg.clusters.items())],
        "users": [{"name": name, "user": {
            k: v for k, v in (("token", u.token),
                              ("tokenFile", u.token_file),
                              ("username", u.username),
                              ("password", u.password)) if v}}
                  for name, u in sorted(cfg.users.items())],
        "contexts": [{"name": name, "context": {
            k: v for k, v in (("cluster", c.cluster), ("user", c.user),
                              ("namespace", c.namespace)) if v}}
                     for name, c in sorted(cfg.contexts.items())],
    }


# the keys each section's dataclass models; everything else in a
# real-world kubeconfig (certificate-authority-data, auth-provider,
# extensions, ...) is preserved verbatim on save
_MODELED = {"cluster": {"server"},
            "user": {"token", "tokenFile", "username", "password"},
            "context": {"cluster", "user", "namespace"}}


def _merge_preserving(existing: dict, new: dict) -> dict:
    """Overlay the modeled fields onto an existing raw config without
    destroying anything this library doesn't model (real kubectl may
    share the file). Per named entry: unmodeled subkeys survive,
    modeled subkeys are replaced wholesale (set-credentials REPLACES a
    user, it must not resurrect an old token)."""
    out = dict(existing)
    # headerless/minimal existing files still get a valid header (real
    # clientcmd validates apiVersion/kind)
    out.setdefault("apiVersion", new["apiVersion"])
    out.setdefault("kind", new["kind"])
    out["current-context"] = new["current-context"]
    for section, subkey in (("clusters", "cluster"), ("users", "user"),
                            ("contexts", "context")):
        old_by_name = {e.get("name"): e
                       for e in existing.get(section) or []}
        merged = []
        seen = set()
        for entry in new[section]:
            name = entry.get("name")
            seen.add(name)
            old = old_by_name.get(name)
            if old is None:
                merged.append(entry)
                continue
            keep = {k: v for k, v in (old.get(subkey) or {}).items()
                    if k not in _MODELED[subkey]}
            merged.append({**old, "name": name,
                           subkey: {**keep, **entry.get(subkey, {})}})
        # entries this library never loaded (no name, exotic shapes)
        merged.extend(e for e in existing.get(section) or []
                      if e.get("name") not in seen)
        out[section] = merged
    return out


def save_kubeconfig(cfg: KubeConfig, path: Optional[str] = None) -> str:
    """Write the config back (ref: clientcmd ModifyConfig: 0600, and
    content this library doesn't model survives the round-trip). YAML
    when available, JSON otherwise (the loader reads both)."""
    path = path or os.environ.get("KUBECONFIG") or DEFAULT_PATH
    data = dump_kubeconfig(cfg)
    try:
        import yaml
        loads, dumps = yaml.safe_load, (
            lambda d: yaml.safe_dump(d, sort_keys=False))
    except ImportError:
        import json
        loads, dumps = json.loads, (lambda d: json.dumps(d, indent=2))
    try:
        with open(path) as f:
            existing = loads(f.read()) or {}
        if isinstance(existing, dict):
            data = _merge_preserving(existing, data)
    except FileNotFoundError:
        pass
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # 0600 even for pre-existing files: the content carries bearer
    # tokens / passwords (os.open's mode only applies on creation)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    os.fchmod(fd, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(dumps(data))
    return path


def client_from_kubeconfig(path: Optional[str] = None,
                           context: str = ""):
    """-> (HttpClient, default_namespace)."""
    from .client import HttpClient

    server, headers, namespace = load_kubeconfig(path).resolve(context)
    return HttpClient(server, headers=headers), namespace
